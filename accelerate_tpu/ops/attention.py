"""Flash attention for TPU in pallas, with an XLA reference fallback.

This is the one op where a hand kernel beats XLA fusion: materializing the
[S, S] score matrix in HBM is the memory wall, and the online-softmax
streaming formulation keeps everything in VMEM. Layout is [batch, heads,
seq, head_dim] (MXU-friendly: the last two dims tile onto the 128x128
systolic array).

The reference framework has no attention kernels at all (it delegates
compute to the wrapped torch model); this op exists because our framework
ships model implementations (models/) whose hot path must be TPU-native.
Long-context ring attention (parallel/context.py) composes with this
kernel as its per-shard inner step.

Capabilities:
- causal or full attention, fp32 accumulation, bf16 in/out
- GQA/MQA native: kv blocks are indexed per query-head group in the
  BlockSpec (`h // group`), so K/V are never expanded to full head count
  and the dk/dv pass sums the group's gradients in-kernel
- padding masks (`kv_mask`) and packed-sequence `segment_ids`, applied
  inside the kernels (padded/packed workloads stay on the flash path)
- custom VJP: pallas forward AND backward (dq and dk/dv kernels)
- `(out, lse)` residual export for the ring-attention inner step
- `interpret=True` runs the same kernels on CPU for tests
- ragged/paged DECODE kernels (`decode_attention` / `paged_decode_attention`
  dispatch): length-aware online-softmax walk over only each slot's live kv
  blocks — straight from the physical page arena through the slot's page
  table, or in fixed blocks over a dense arena — so decode HBM traffic
  scales with live tokens, not arena capacity. Masked-dense stays the
  fallback + bit-exactness reference (`ATT_DECODE_KERNEL=paged|dense`,
  "interpret" for CPU tests)
- quantized KV arenas (`kv_quant_bits=8|4` + per-token scale operands):
  both decode kernels read int8/packed-int4 payloads from HBM and
  dequantize in-register before the flash inner product, so the byte
  shrink compounds with the live-token walk; the masked-dense fallback
  dequantizes via the same reference op sequence
  (utils/quantization.dequantize_kv) and stays the exactness oracle
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import importlib


class _LazyModule:
    """Deferred import: pallas costs ~0.2 s at import time, which lands on
    every process's startup (the TTFT bench counts it) even when the process
    never traces a kernel. Resolution happens at first attribute access —
    i.e. at trace time, inside the first jit."""

    def __init__(self, name):
        self._name = name
        self._mod = None

    def _resolve(self):
        if self._mod is None:
            self._mod = importlib.import_module(self._name)
        return self._mod

    def __getattr__(self, attr):
        return getattr(self._resolve(), attr)


pl = _LazyModule("jax.experimental.pallas")
_pltpu_lazy = _LazyModule("jax.experimental.pallas.tpu")


class _PltpuProxy:
    """pallas TPU backend is absent on some CPU-only jaxlib builds; probe
    lazily. Truthiness mirrors availability so `if pltpu:` keeps the old
    None semantics."""

    def __getattr__(self, attr):
        return getattr(_pltpu_lazy._resolve(), attr)

    def __bool__(self):
        return _has_pltpu()


pltpu = _PltpuProxy()


def _has_pltpu() -> bool:
    try:
        _pltpu_lazy._resolve()
        return True
    except Exception:  # pragma: no cover
        return False

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() semantics with no NaN risk


# ---------------------------------------------------------------------------
# XLA reference (CPU fallback + ground truth for kernel tests)
# ---------------------------------------------------------------------------


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain-XLA attention. q: [B, H, Sq, D]; k/v: [B, KVH, Skv, D].
    ``bias`` is additive, broadcastable to [B, H, Sq, Skv] (use large
    negatives for padding masks)."""
    orig_dtype = q.dtype
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, h, sq, d = q.shape
    kvh = k.shape[1]
    if kvh != h:
        group = h // kvh
        q = q.reshape(b, kvh, group, sq, d)
        s = jnp.einsum("bkgqd,bkcd->bkgqc", q, k, preferred_element_type=jnp.float32)
    else:
        s = jnp.einsum("bhqd,bhcd->bhqc", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if bias is not None:
        bias32 = jnp.broadcast_to(bias.astype(jnp.float32), (b, h, sq, k.shape[2]))
        if kvh != h:
            bias32 = bias32.reshape(b, kvh, group, sq, k.shape[2])
        s = s + bias32
    if causal:
        skv = k.shape[2]
        mask = jnp.tril(jnp.ones((sq, skv), dtype=bool), k=skv - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if kvh != h:
        out = jnp.einsum("bkgqc,bkcd->bkgqd", p.astype(v.dtype), v)
        out = out.reshape(b, h, sq, d)
    else:
        out = jnp.einsum("bhqc,bhcd->bhqd", p.astype(v.dtype), v)
    return out.astype(orig_dtype)


# ---------------------------------------------------------------------------
# pallas kernels
#
# All kernels take the optional mask refs (kv_mask [B, Skv] int32 — nonzero
# = attend; q_seg/kv_seg [B, S] int32 — attend iff equal) threaded by
# compile-time has_* flags, and handle GQA by kv-head block indexing.
# ---------------------------------------------------------------------------


def _parse_refs(args, n_out, has_kv_mask, has_seg):
    """Split pallas's positional (in_refs..., out_refs..., scratch...) by
    the kernel's compile-time mask flags."""
    i = 3
    kv_mask_ref = q_seg_ref = kv_seg_ref = None
    if has_kv_mask:
        kv_mask_ref = args[i]
        i += 1
    if has_seg:
        q_seg_ref, kv_seg_ref = args[i], args[i + 1]
        i += 2
    outs = args[i : i + n_out]
    scratch = args[i + n_out :]
    return args[0], args[1], args[2], kv_mask_ref, q_seg_ref, kv_seg_ref, outs, scratch


def _mask_block(s, kv_mask_ref, q_seg_ref, kv_seg_ref, causal, iq, ik, bq, bk):
    """Apply causal / padding / segment masks to a [bq, bk] score block.
    Returns (masked scores, bool validity matrix or None)."""
    valid = None
    if causal:
        rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = cols <= rows
    if kv_mask_ref is not None:
        kvm = kv_mask_ref[0, 0] != 0  # [bk] (mask blocks are [1, 1, bk])
        m = jnp.broadcast_to(kvm[None, :], (bq, bk))
        valid = m if valid is None else (valid & m)
    if q_seg_ref is not None:
        qs = q_seg_ref[0, 0]  # [bq]
        ks = kv_seg_ref[0, 0]  # [bk]
        m = qs[:, None] == ks[None, :]
        valid = m if valid is None else (valid & m)
    if valid is not None:
        s = jnp.where(valid, s, NEG_INF)
    return s, valid


def _fwd_kernel(*args, sm_scale, causal, bq, bk, nk, has_kv_mask, has_seg):
    q_ref, k_ref, v_ref, kv_mask_ref, q_seg_ref, kv_seg_ref, outs, scratch = _parse_refs(
        args, 2, has_kv_mask, has_seg
    )
    o_ref, lse_ref = outs
    acc, m_scr, l_scr = scratch
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    # causal: skip kv blocks entirely above the diagonal
    run = (iq + 1) * bq > ik * bk if causal else ik >= 0

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        s, _ = _mask_block(s, kv_mask_ref, q_seg_ref, kv_seg_ref, causal, iq, ik, bq, bk)
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _out():
        l = l_scr[...][:, :1]
        m = m_scr[...][:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[...] / safe_l).astype(o_ref.dtype)
        # TPU tiling: lse lives as [B, H, 8, Sq] (one f32 sublane tile);
        # row 0 is the value, rows 1-7 are padding. Fully-masked rows keep
        # lse = NEG_INF (l == 0) so downstream merges treat them as empty.
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(safe_l))
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, 0][None, :], lse_ref.shape[2:])


def _p_from_lse(s, lse, valid):
    """exp(s - lse) with masked entries forced to exactly 0 (a fully masked
    row has lse = NEG_INF, where s - lse would be 0 -> p 1 -> garbage)."""
    p = jnp.exp(s - lse)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    return p


def _dq_kernel(*args, sm_scale, causal, bq, bk, nk, has_kv_mask, has_seg):
    # in_refs: q, k, v, do, lse, delta, [kv_mask], [q_seg, kv_seg]
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = args[:6]
    i = 6
    kv_mask_ref = q_seg_ref = kv_seg_ref = None
    if has_kv_mask:
        kv_mask_ref = args[i]
        i += 1
    if has_seg:
        q_seg_ref, kv_seg_ref = args[i], args[i + 1]
        i += 2
    dq_ref = args[i]
    dq_acc = args[i + 1]
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (iq + 1) * bq > ik * bk if causal else ik >= 0

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0][:, None]
        delta = delta_ref[0, 0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * sm_scale
        s, valid = _mask_block(s, kv_mask_ref, q_seg_ref, kv_seg_ref, causal, iq, ik, bq, bk)
        p = _p_from_lse(s, lse, valid)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32), (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ik == nk - 1)
    def _out():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(*args, sm_scale, causal, bq, bk, nq_total, nq, has_kv_mask, has_seg):
    """dk/dv for one kv head. Grid dim 3 runs over nq_total = nq * group
    query blocks (all blocks of every query head in this kv head's group),
    so the group's gradients sum into the kv head in-kernel — GQA without
    expanding K/V."""
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = args[:6]
    i = 6
    kv_mask_ref = q_seg_ref = kv_seg_ref = None
    if has_kv_mask:
        kv_mask_ref = args[i]
        i += 1
    if has_seg:
        q_seg_ref, kv_seg_ref = args[i], args[i + 1]
        i += 2
    dk_ref, dv_ref = args[i], args[i + 1]
    dk_acc, dv_acc = args[i + 2], args[i + 3]
    ik, it = pl.program_id(2), pl.program_id(3)
    iq = it % nq  # query-block index within the current group member

    @pl.when(it == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (iq + 1) * bq > ik * bk if causal else it >= 0

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0][:, None]
        delta = delta_ref[0, 0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * sm_scale
        s, valid = _mask_block(s, kv_mask_ref, q_seg_ref, kv_seg_ref, causal, iq, ik, bq, bk)
        p = _p_from_lse(s, lse, valid)  # [bq, bk]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v.astype(jnp.float32), (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale  # [bq, bk]
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(it == nq_total - 1)
    def _out():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _pick_block(s: int, preferred: int) -> int:
    # 1024 first: measured ~30% faster than 512 blocks across 2k-16k
    # sequences on v5e (fwd+bwd); 2048 blocks exceed VMEM
    for cand in (preferred, 1024, 512, 256, 128):
        if cand <= s and s % cand == 0:
            return cand
    return 0  # no valid block → caller falls back to XLA


def _compiler_params(dimension_semantics):
    """Mosaic compiler params across jax versions: 0.4.x spells the class
    ``TPUCompilerParams``; newer builds renamed it ``CompilerParams``."""
    mod = _pltpu_lazy._resolve()
    cls = getattr(mod, "CompilerParams", None) or getattr(mod, "TPUCompilerParams")
    return cls(dimension_semantics=dimension_semantics)


def _grid_params(
    interpret: bool,
    semantics=("parallel", "parallel", "parallel", "arbitrary"),
):
    kw = {"interpret": interpret}
    if not interpret and _has_pltpu():
        kw["compiler_params"] = _compiler_params(semantics)
    return kw


def _mask_specs(masks, bq, bk, group):
    """(in_specs, arrays) for the optional kv_mask / segment-id inputs.
    kv-indexed arrays block over ik; q-indexed over iq. Masks carry an
    explicit singleton sublane dim ([B, 1, S], block (1, 1, blk)) to satisfy
    the TPU (8, 128) block-tiling rule."""
    kv_mask, q_seg, kv_seg = masks
    specs, arrays = [], []
    if kv_mask is not None:
        specs.append(pl.BlockSpec((1, 1, bk), lambda b_, h_, iq, ik: (b_, 0, ik)))
        arrays.append(kv_mask.astype(jnp.int32)[:, None, :])
    if q_seg is not None:
        specs.append(pl.BlockSpec((1, 1, bq), lambda b_, h_, iq, ik: (b_, 0, iq)))
        arrays.append(q_seg.astype(jnp.int32)[:, None, :])
        specs.append(pl.BlockSpec((1, 1, bk), lambda b_, h_, iq, ik: (b_, 0, ik)))
        arrays.append(kv_seg.astype(jnp.int32)[:, None, :])
    return specs, arrays


def _flash_fwd_call(q, k, v, masks, causal, sm_scale, bq, bk, interpret):
    b, h, sq, d = q.shape
    kvh, skv = k.shape[1], k.shape[2]
    group = h // kvh
    nq, nk = sq // bq, skv // bk
    kv_mask, q_seg, kv_seg = masks
    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        bq=bq,
        bk=bk,
        nk=nk,
        has_kv_mask=kv_mask is not None,
        has_seg=q_seg is not None,
    )
    mask_specs, mask_arrays = _mask_specs(masks, bq, bk, group)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_ // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_ // group, ik, 0)),
            *mask_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda b_, h_, iq, ik: (b_, h_, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 8, sq), jnp.float32),
        ],
        scratch_shapes=[_vmem((bq, d)), _vmem((bq, 128)), _vmem((bq, 128))],
        **_grid_params(interpret),
    )(q, k, v, *mask_arrays)
    return out, lse


def _flash_bwd_call(q, k, v, out, lse, do, masks, causal, sm_scale, bq, bk, interpret):
    b, h, sq, d = q.shape
    kvh, skv = k.shape[1], k.shape[2]
    group = h // kvh
    nq, nk = sq // bq, skv // bk
    kv_mask, q_seg, kv_seg = masks
    has_kv_mask, has_seg = kv_mask is not None, q_seg is not None
    lse = jnp.broadcast_to(lse, (b, h, 8, sq))  # residual stored [B,H,1,Sq]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,Sq]
    delta = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, sq))  # sublane-tile layout

    mask_specs, mask_arrays = _mask_specs(masks, bq, bk, group)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, bq=bq, bk=bk, nk=nk,
            has_kv_mask=has_kv_mask, has_seg=has_seg,
        ),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_ // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_ // group, ik, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda b_, h_, iq, ik: (b_, h_, 0, iq)),
            pl.BlockSpec((1, 1, 8, bq), lambda b_, h_, iq, ik: (b_, h_, 0, iq)),
            *mask_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_vmem((bq, d))],
        **_grid_params(interpret),
    )(q, k, v, do, lse, delta, *mask_arrays)

    # dk/dv: grid over kv heads; innermost dim covers every (group member,
    # query block) pair so the group's grads accumulate into one kv block
    nq_total = nq * group

    def _qh(kv_, it):  # query head for this grid step
        return kv_ * group + it // nq

    # q-indexed mask specs need the (kv_, it) index layout of this grid
    mask_specs_kv = []
    if has_kv_mask:
        mask_specs_kv.append(pl.BlockSpec((1, 1, bk), lambda b_, kv_, ik, it: (b_, 0, ik)))
    if has_seg:
        mask_specs_kv.append(pl.BlockSpec((1, 1, bq), lambda b_, kv_, ik, it: (b_, 0, it % nq)))
        mask_specs_kv.append(pl.BlockSpec((1, 1, bk), lambda b_, kv_, ik, it: (b_, 0, ik)))

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, bq=bq, bk=bk,
            nq_total=nq_total, nq=nq, has_kv_mask=has_kv_mask, has_seg=has_seg,
        ),
        grid=(b, kvh, nk, nq_total),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, kv_, ik, it: (b_, _qh(kv_, it), it % nq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, kv_, ik, it: (b_, kv_, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, kv_, ik, it: (b_, kv_, ik, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, kv_, ik, it: (b_, _qh(kv_, it), it % nq, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda b_, kv_, ik, it: (b_, _qh(kv_, it), 0, it % nq)),
            pl.BlockSpec((1, 1, 8, bq), lambda b_, kv_, ik, it: (b_, _qh(kv_, it), 0, it % nq)),
            *mask_specs_kv,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, kv_, ik, it: (b_, kv_, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, kv_, ik, it: (b_, kv_, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[_vmem((bk, d)), _vmem((bk, d))],
        **_grid_params(interpret),
    )(q, k, v, do, lse, delta, *mask_arrays)
    return dq, dk, dv


def _vmem(shape):
    if not _has_pltpu():  # pragma: no cover
        raise RuntimeError("pallas TPU memory spaces unavailable in this jaxlib build")
    return pltpu.VMEM(shape, jnp.float32)


# ---------------------------------------------------------------------------
# custom-VJP core. q [B, H, Sq, D]; k/v [B, KVH, Skv, D] (KVH divides H).
# ``masks`` is a tuple (kv_mask | None, q_seg | None, kv_seg | None) — int
# arrays are non-differentiable, their cotangent is None.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, masks, causal, sm_scale, bq, bk, interpret):
    out, _ = _flash_fwd_call(q, k, v, masks, causal, sm_scale, bq, bk, interpret)
    return out


def _flash_core_fwd(q, k, v, masks, causal, sm_scale, bq, bk, interpret):
    out, lse = _flash_fwd_call(q, k, v, masks, causal, sm_scale, bq, bk, interpret)
    # keep only the value row of the [B,H,8,Sq] tile layout as the residual.
    # checkpoint_name lets a remat policy (models/configs.remat_policy =
    # "save_attention") KEEP these residuals so the backward pass reuses the
    # kernel's out/lse instead of re-running the whole forward kernel —
    # at 16k+ tokens the attention recompute is the largest remat term.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse[:, :, :1], "flash_lse")
    return out, (q, k, v, masks, out, lse)


def _flash_core_bwd(causal, sm_scale, bq, bk, interpret, res, do):
    q, k, v, masks, out, lse = res
    dq, dk, dv = _flash_bwd_call(q, k, v, out, lse, do, masks, causal, sm_scale, bq, bk, interpret)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
) -> jax.Array:
    """Pallas flash attention. q: [B, H, Sq, D]; k/v: [B, KVH, Skv, D]
    (KVH must divide H — kv blocks are shared across the query-head group in
    the kernel; K/V are never expanded).

    ``kv_mask`` [B, Skv]: nonzero = position may be attended (padding mask).
    ``q_segment_ids``/``kv_segment_ids`` [B, S]: tokens attend only within
    equal segment ids (packed sequences)."""
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    h, kvh = q.shape[1], k.shape[1]
    if h % kvh:
        raise ValueError(f"query heads ({h}) must be a multiple of kv heads ({kvh})")
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("q_segment_ids and kv_segment_ids must be given together")
    bq = _pick_block(q.shape[2], block_q)
    bk = _pick_block(k.shape[2], block_kv)
    if not bq or not bk:
        raise ValueError(
            f"sequence lengths ({q.shape[2]}, {k.shape[2]}) need a 128-multiple block; "
            "pad inputs or use dot_product_attention (auto-fallback)"
        )
    masks = (kv_mask, q_segment_ids, kv_segment_ids)
    return _flash_core(q, k, v, masks, causal, sm_scale, bq, bk, interpret)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
):
    """Forward-only flash attention returning (out, lse [B, H, Sq] fp32).
    The ring-attention inner step (parallel/context.py) builds its own
    ring-level VJP from this plus the dq/dkv kernels below."""
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    bq = _pick_block(q.shape[2], block_q)
    bk = _pick_block(k.shape[2], block_kv)
    if not bq or not bk:
        raise ValueError("sequence lengths need a 128-multiple block")
    masks = (kv_mask, None, None)
    out, lse = _flash_fwd_call(q, k, v, masks, causal, sm_scale, bq, bk, interpret)
    return out, lse[:, :, 0]


def flash_attention_bwd(
    q, k, v, out, lse, do, *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
):
    """Block gradients given a (possibly global) lse [B, H, Sq]: returns
    (dq, dk, dv) for this q/kv block pair. With p = exp(s - lse), partial
    contributions sum correctly across kv blocks — which is exactly what the
    ring backward needs."""
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    bq = _pick_block(q.shape[2], block_q)
    bk = _pick_block(k.shape[2], block_kv)
    if not bq or not bk:
        raise ValueError("sequence lengths need a 128-multiple block")
    masks = (kv_mask, None, None)
    return _flash_bwd_call(
        q, k, v, out, lse[:, :, None, :], do, masks, causal, sm_scale, bq, bk, interpret
    )


# ---------------------------------------------------------------------------
# pallas ragged/paged decode-attention kernel (ROADMAP item 2)
#
# The masked-dense decode read streams the WHOLE arena reservation through
# HBM every step — decode bandwidth scales with capacity, not live tokens.
# These kernels walk only each slot's live KV blocks: a (slots × kv-heads ×
# kv-blocks) grid with flash-style online softmax, where blocks past a
# slot's frontier are clamped to the last live block in the BlockSpec index
# map (the pipeline elides the re-fetch of an unchanged block, so dead
# blocks cost neither DMA nor compute) and skipped by ``pl.when``. The
# paged variant reads K/V straight from the physical page arena
# ([num_pages, KVH, page_size, D]) through each slot's device page table
# (scalar-prefetched so the table drives the index maps); the dense variant
# walks a [B, KVH, L, D] arena in fixed blocks — the same win for the
# single-stream decode loop and the flat slot arena. GQA folds the query
# head group (× the Sq query rows: the multi-query form spec_verify and
# fused bursts use) into one [group*Sq, D] block per kv head, so K/V are
# never expanded.
# ---------------------------------------------------------------------------

_DECODE_KERNEL_MODES = ("paged", "dense", "interpret")
# multi-query width the kernel accepts: decode (1), fused bursts (1/step),
# speculative verify (K+1). Prefill-size chunks (64+) stay on the dense
# path by design — they are compute-shaped, and the row-position unroll
# below is linear in Sq.
_DECODE_KERNEL_MAX_SQ = 16
_decode_fallback_warned: set = set()


def resolve_decode_kernel(impl: Optional[str] = None) -> str:
    """Resolve the decode-attention implementation choice: the explicit
    ``impl`` (``DecoderConfig.decode_kernel``) wins, else the
    ``ATT_DECODE_KERNEL`` env knob, else ``"paged"`` (the kernel, with a
    warn-once dense fallback off-TPU). ``"interpret"`` runs the same kernel
    through the pallas interpreter — the CPU test/CI mode."""
    mode = impl or os.environ.get("ATT_DECODE_KERNEL", "paged")
    if mode not in _DECODE_KERNEL_MODES:
        raise ValueError(
            f"ATT_DECODE_KERNEL/decode_kernel must be one of "
            f"{_DECODE_KERNEL_MODES}, got {mode!r}"
        )
    return mode


def _warn_once(key: str, message: str, *args):
    if key in _decode_fallback_warned:
        return
    _decode_fallback_warned.add(key)
    import logging

    logging.getLogger(__name__).warning(message, *args)


def _warn_decode_fallback(reason: str):
    """Warn-once per distinct reason (mirrors the fp8-without-MXU warn):
    the paged decode kernel was requested (or defaulted) but this process
    silently runs the masked-dense path instead, so decode bandwidth
    scales with arena capacity, not live tokens."""
    _warn_once(
        reason,
        "paged decode-attention kernel unavailable (%s); falling back to "
        "the masked-dense read — decode HBM traffic will scale with the "
        "arena reservation, not live tokens. Set ATT_DECODE_KERNEL=dense "
        "(or DecoderConfig.decode_kernel='dense') to silence, or "
        "'interpret' to run the kernel through the pallas interpreter.",
        reason,
    )


def _decode_kernel_gate(mode: str, sq: int, d: int, blk: int,
                        quant_bits: int = 0):
    """(use_kernel, interpret) for one dispatch. Falls back silently for
    by-design exclusions (``dense`` mode, prefill-size Sq) and with a
    warn-once for environment/shape gates. ``quant_bits`` extends the
    compiled-mode shape rule to the operands the quantized kernel
    actually loads: int4's packed payload blocks are ``d // 2`` wide, so
    the lane-multiple rule applies to THAT width — without it, an
    unsupported tiling would surface as a Mosaic compile error instead
    of the dense fallback.

    Compiled head_dim floor is 64, not 128: a 64-wide head block maps
    onto the 128-lane tile as a narrow tile Mosaic lane-pads internally,
    trading lane occupancy on the K/V loads for keeping the live-token
    walk — still far ahead of the masked-dense read that streams the
    whole arena reservation. int4 packs the payload to ``d // 2``, so
    its compiled floor is head_dim 128 (was 256)."""
    if mode == "dense":
        return False, False
    if sq > _DECODE_KERNEL_MAX_SQ:
        return False, False
    if blk <= 0:
        _warn_decode_fallback("no valid kv block size for this cache length")
        return False, False
    if not _has_pltpu():
        _warn_decode_fallback("pallas TPU support missing from this jaxlib")
        return False, False
    if mode == "interpret":
        return True, True
    if jax.default_backend() != "tpu":
        _warn_decode_fallback(f"no TPU backend ({jax.default_backend()} process)")
        return False, False
    if d % 64 != 0 or blk % 8 != 0:
        _warn_decode_fallback(
            f"shape gate: head_dim {d} must be a 64-multiple (64 compiles "
            f"as a lane-padded narrow tile) and the kv block/page size "
            f"{blk} an 8-multiple for the compiled kernel; this dispatch "
            "resolves to the gathered dequant + masked-dense read"
        )
        return False, False
    if quant_bits == 4 and (d // 2) % 64 != 0:
        _warn_decode_fallback(
            f"shape gate: int4 KV packs the payload to head_dim/2 = "
            f"{d // 2}, which must itself be a 64-multiple for the "
            "compiled kernel (head_dim a 128-multiple); this dispatch "
            "resolves to the gathered dequant + masked-dense read"
        )
        return False, False
    return True, False


def decode_kernel_active(config, sq: int = 1) -> bool:
    """Would a paged decode dispatch of query width ``sq`` (1 = the plain
    decode step; spec_draft_len+1 = the verify program) on a model with
    this config run the pallas kernel in this process? The serving engine
    and bench use this to decide whether a dispatch bills the
    ``paged_decode_kernel`` roofline row — it must mirror
    :func:`paged_decode_attention`'s gate exactly, or the row would claim
    bandwidth a fallback path never achieved."""
    page_size = getattr(config, "kv_page_size", None)
    if not page_size:
        return False
    mode = resolve_decode_kernel(getattr(config, "decode_kernel", None))
    if mode == "dense":
        return False
    head_dim = int(getattr(config, "head_dim", 0) or 0)
    quant_bits = {"int8": 8, "int4": 4}.get(
        getattr(config, "kv_cache_dtype", "bf16"), 0
    )
    use, _ = _decode_kernel_gate(mode, sq, head_dim, int(page_size), quant_bits)
    return use


def _pick_decode_block(length: int, preferred: Optional[int], interpret: bool) -> int:
    """kv block for the dense-arena decode kernel: the largest candidate
    dividing the cache length. Smaller blocks exit earlier on short live
    lengths; bigger blocks amortize grid overhead — 256 measured best on
    2k-8k arenas (the same trade as ``_pick_block``, at decode's smaller
    working set). Interpret mode (CPU tests) admits tiny blocks the TPU
    tiling rules would reject."""
    cands = ([int(preferred)] if preferred else []) + [512, 256, 128, 64, 32, 16]
    if interpret:
        cands += [8, 4, 2, 1]
    for cand in cands:
        if 0 < cand <= length and length % cand == 0:
            return cand
    return 0


def _decode_kernel_body(maxblk_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                        acc, m_scr, l_scr, *, sm_scale, bk, sq, group,
                        quant_bits=0, out_dtype=None,
                        ks_ref=None, vs_ref=None):
    """Online-softmax accumulation over one slot's kv blocks — shared by
    the paged and dense-arena variants (only the BlockSpec index maps
    differ). Grid is (B, KVH, n_blocks) with the block dim innermost
    ("arbitrary"); blocks past ``maxblk_ref[b]`` (the slot's last live
    block) are skipped — their operand fetch was already elided by the
    clamped index map. Per-element validity is ``kv position <= the query
    row's position``, the exact mask of the dense reference, so parked /
    stale / rolled-back entries inside a live block contribute exactly
    zero probability.

    ``quant_bits`` (8/4) turns on KERNEL-FUSED DEQUANT: ``k_ref``/``v_ref``
    hold int8 payloads (int4 packs two values per byte along head_dim) and
    ``ks_ref``/``vs_ref`` the per-(token, kv-head) fp32 scales; blocks load
    quantized from HBM — the byte shrink compounds with the live-token walk
    — and dequantize in-register via ``utils.quantization.dequantize_kv``,
    the same op sequence the masked-dense reference runs, so the oracle
    contract survives quantization."""
    b, ib = pl.program_id(0), pl.program_id(2)
    nb = pl.num_programs(2)
    g = group * sq

    @pl.when(ib == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    @pl.when(ib <= maxblk_ref[b])
    def _body():
        q = q_ref[0, 0]  # [G, D] — the kv head's query group × Sq rows
        k = k_ref[0, 0]  # [bk, D] (quantized: int8 payload [bk, D or D/2])
        v = v_ref[0, 0]
        if quant_bits:
            from ..utils.quantization import dequantize_kv

            k = dequantize_kv(k, ks_ref[0, 0], quant_bits, out_dtype)
            v = dequantize_kv(v, vs_ref[0, 0], quant_bits, out_dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        kvpos = ib * bk + jax.lax.broadcasted_iota(jnp.int32, (g, bk), 1)
        if sq == 1:
            rowpos = jnp.full((g, bk), pos_ref[b, 0], jnp.int32)
        else:
            # row r of the [group, Sq] fold is query token t = r % sq;
            # sq is compile-time small (<= _DECODE_KERNEL_MAX_SQ), so the
            # scalar reads unroll
            t_idx = jax.lax.broadcasted_iota(jnp.int32, (g, bk), 0) % sq
            rowpos = jnp.zeros((g, bk), jnp.int32)
            for t in range(sq):
                rowpos = jnp.where(t_idx == t, pos_ref[b, t], rowpos)
        s = jnp.where(kvpos <= rowpos, s, NEG_INF)
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_scr[...] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape
        )
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)

    @pl.when(ib == nb - 1)
    def _out():
        l = l_scr[...][:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[...] / safe_l).astype(o_ref.dtype)


def _paged_kernel_entry(maxblk_ref, pos_ref, table_ref, q_ref, k_ref, v_ref,
                        o_ref, acc, m_scr, l_scr, **kw):
    # the page table is consumed by the index maps only
    _decode_kernel_body(maxblk_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                        acc, m_scr, l_scr, **kw)


def _paged_quant_kernel_entry(maxblk_ref, pos_ref, table_ref, q_ref, k_ref,
                              v_ref, ks_ref, vs_ref, o_ref, acc, m_scr,
                              l_scr, **kw):
    # quantized arena: two extra scale operands ride the same clamped
    # page-table index maps as their payloads
    _decode_kernel_body(maxblk_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                        acc, m_scr, l_scr, ks_ref=ks_ref, vs_ref=vs_ref, **kw)


def _dense_quant_kernel_entry(maxblk_ref, pos_ref, q_ref, k_ref, v_ref,
                              ks_ref, vs_ref, o_ref, acc, m_scr, l_scr, **kw):
    _decode_kernel_body(maxblk_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                        acc, m_scr, l_scr, ks_ref=ks_ref, vs_ref=vs_ref, **kw)


def _decode_grid_params(interpret: bool):
    # the decode grid is 3-dim (slots, kv-heads, kv-blocks); only the
    # block walk is sequential
    return _grid_params(interpret, ("parallel", "parallel", "arbitrary"))


def _fold_q_heads(q, kvh):
    """[B, H, Sq, D] -> [B, KVH, group*Sq, D]: query heads of one kv head's
    group (plus their Sq rows) become one MXU-friendly block. Pure reshape
    — H is laid out [kv0's group, kv1's group, ...] (the ``h // group``
    BlockSpec convention of the flash kernels)."""
    b, h, sq, d = q.shape
    return q.reshape(b, kvh, (h // kvh) * sq, d)


def _positions_2d(q_positions, b):
    pos = jnp.asarray(q_positions, jnp.int32)
    if pos.ndim == 1:  # [Sq] shared across the batch
        pos = jnp.broadcast_to(pos[None, :], (b, pos.shape[0]))
    return pos


def _paged_decode_kernel_call(q, k_pages, v_pages, page_table, pos,
                              sm_scale, interpret, k_scale=None,
                              v_scale=None, quant_bits=0):
    b, h, sq, d = q.shape
    _, kvh, ps, pd = k_pages.shape  # pd: payload width (d, or d/2 packed int4)
    group = h // kvh
    g = group * sq
    n_blocks = page_table.shape[1]
    q_r = _fold_q_heads(q, kvh)
    # last live BLOCK per slot: index maps clamp here so dead grid steps
    # re-address the same page (fetch elided), pl.when skips their compute
    maxblk = (jnp.max(pos, axis=1) // ps).astype(jnp.int32)
    entry = _paged_quant_kernel_entry if quant_bits else _paged_kernel_entry
    kernel = functools.partial(
        entry, sm_scale=sm_scale, bk=ps, sq=sq, group=group,
        quant_bits=quant_bits, out_dtype=q.dtype,
    )

    def _page_spec(width):
        return pl.BlockSpec(
            (1, 1, ps, width),
            lambda b_, h_, ib, mb, po, tb: (tb[b_, jnp.minimum(ib, mb[b_])], h_, 0, 0),
        )

    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda b_, h_, ib, mb, po, tb: (b_, h_, 0, 0)),
        _page_spec(pd),
        _page_spec(pd),
    ]
    operands = [q_r, k_pages, v_pages]
    if quant_bits:
        # per-(page, kv-head, token) fp32 scales ride the same clamped
        # table walk as their payload pages
        in_specs += [_page_spec(1), _page_spec(1)]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, kvh, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d), lambda b_, h_, ib, mb, po, tb: (b_, h_, 0, 0)),
        scratch_shapes=[_vmem((g, d)), _vmem((g, 128)), _vmem((g, 128))],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, g, d), q.dtype),
        **_decode_grid_params(interpret),
    )(maxblk, pos, page_table.astype(jnp.int32), *operands)
    return out.reshape(b, h, sq, d)


def _dense_decode_kernel_call(q, k, v, pos, sm_scale, bk, interpret,
                              k_scale=None, v_scale=None, quant_bits=0):
    b, h, sq, d = q.shape
    kvh, length, pd = k.shape[1], k.shape[2], k.shape[3]
    group = h // kvh
    g = group * sq
    q_r = _fold_q_heads(q, kvh)
    maxblk = (jnp.max(pos, axis=1) // bk).astype(jnp.int32)
    entry = _dense_quant_kernel_entry if quant_bits else _decode_kernel_body
    kernel = functools.partial(
        entry, sm_scale=sm_scale, bk=bk, sq=sq, group=group,
        quant_bits=quant_bits, out_dtype=q.dtype,
    )

    def _kv_spec(width):
        return pl.BlockSpec(
            (1, 1, bk, width),
            lambda b_, h_, ib, mb, po: (b_, h_, jnp.minimum(ib, mb[b_]), 0),
        )

    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda b_, h_, ib, mb, po: (b_, h_, 0, 0)),
        _kv_spec(pd),
        _kv_spec(pd),
    ]
    operands = [q_r, k, v]
    if quant_bits:
        in_specs += [_kv_spec(1), _kv_spec(1)]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, length // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d), lambda b_, h_, ib, mb, po: (b_, h_, 0, 0)),
        scratch_shapes=[_vmem((g, d)), _vmem((g, 128)), _vmem((g, 128))],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, g, d), q.dtype),
        **_decode_grid_params(interpret),
    )(maxblk, pos, *operands)
    return out.reshape(b, h, sq, d)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_positions: jax.Array,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
    block_kv: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    kv_quant_bits: int = 0,
) -> jax.Array:
    """Masked KV-cache decode attention with per-row validity.

    q: [B, H, Sq, D]; k/v: [B, KVH, L, D] — the full (static-length) cache
    arena, already containing the query rows' own K/V. ``q_positions`` is
    the GLOBAL position of each query row: shape [Sq] (shared across the
    batch — the single-stream decode/chunked-prefill case) or [B, Sq]
    (per-slot positions — the continuous-batching case, where every batch
    row is an independent request at its own cache depth). A query attends
    cache slot c iff ``c <= its position``, so per-slot cache lengths are
    respected and slots beyond a request's frontier (stale garbage from a
    previous occupant, padding from a bucketed prefill chunk) contribute
    exactly zero probability.

    Dispatch: at decode widths (Sq <= 16) the length-aware pallas kernel
    reads only the live kv blocks (HBM traffic ∝ live tokens, not L) on
    TPU — or through the interpreter under ``impl='interpret'`` — per
    :func:`resolve_decode_kernel` (``impl`` / ``ATT_DECODE_KERNEL``,
    default "paged" with a warn-once dense fallback off-TPU). Prefill-size
    chunks and the ``dense`` mode run the masked-dense XLA path, which
    stays the bit-exactness reference. ``block_kv`` tunes the kernel's kv
    block (must divide L; default: largest of 512..16 that does).

    ``kv_quant_bits`` (8/4, with ``k_scale``/``v_scale`` [B, KVH, L, 1]
    fp32): k/v hold int8 payloads (int4 packed two-per-byte along D) — the
    kernel path dequantizes IN-REGISTER after the quantized HBM read; the
    masked-dense path runs the reference ``dequantize_kv`` first and stays
    the exactness oracle.
    """
    mode = resolve_decode_kernel(impl)
    sq, d = q.shape[2], q.shape[3]
    if kv_quant_bits and (k_scale is None or v_scale is None):
        raise ValueError("kv_quant_bits needs k_scale and v_scale")
    if mode != "dense":
        bk = _pick_decode_block(k.shape[2], block_kv, mode == "interpret")
        if block_kv and bk and bk != int(block_kv):
            _warn_once(
                f"block_kv {block_kv}/{k.shape[2]}",
                "decode_kernel_block %s does not divide the cache length "
                "%s; the dense-arena decode kernel is using block %s "
                "instead — pick a divisor to make the knob effective.",
                block_kv, k.shape[2], bk,
            )
        use, interpret = _decode_kernel_gate(mode, sq, d, bk, kv_quant_bits)
        if use:
            scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
            pos = _positions_2d(q_positions, q.shape[0])
            return _dense_decode_kernel_call(
                q, k, v, pos, scale, bk, interpret,
                k_scale=k_scale, v_scale=v_scale, quant_bits=kv_quant_bits,
            )
    if kv_quant_bits:
        from ..utils.quantization import dequantize_kv

        k = dequantize_kv(k, k_scale, kv_quant_bits, q.dtype)
        v = dequantize_kv(v, v_scale, kv_quant_bits, q.dtype)
    kv_pos = jnp.arange(k.shape[2])
    if q_positions.ndim == 1:  # [Sq] shared positions
        bias = jnp.where(kv_pos[None, :] <= q_positions[:, None], 0.0, NEG_INF)
        bias = bias[None, None]  # [1, 1, Sq, L]
    else:  # [B, Sq] per-slot positions
        bias = jnp.where(
            kv_pos[None, None, :] <= q_positions[:, :, None], 0.0, NEG_INF
        )[:, None]  # [B, 1, Sq, L]
    return mha_reference(q, k, v, causal=False, sm_scale=sm_scale, bias=bias)


def gather_kv_pages(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """Materialize per-slot dense K (or V) from a paged arena.

    ``pages``: [num_pages, KVH, page_size, D] physical pages; ``page_table``:
    [B, P] int32 page ids per slot (row p of the result's length axis is
    global position p: the table is position-ordered, so ``page_table[b, c]``
    holds positions ``[c*page_size, (c+1)*page_size)``). Returns
    [B, KVH, P*page_size, D]. Duplicate table entries (the parking page
    padding unallocated tail entries) are fine — their rows sit beyond the
    slot's frontier and the decode mask zeroes them.
    """
    g = pages[page_table]                      # [B, P, KVH, page_size, D]
    g = jnp.swapaxes(g, 1, 2)                  # [B, KVH, P, page_size, D]
    b, kvh, p, ps, d = g.shape
    return g.reshape(b, kvh, p * ps, d)


def paged_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    *,
    page_table: jax.Array,
    q_positions: jax.Array,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    kv_quant_bits: int = 0,
) -> jax.Array:
    """Decode attention reading K/V through a per-slot page table.

    q: [B, H, Sq, D]; k_pages/v_pages: [num_pages, KVH, page_size, D];
    ``page_table`` [B, P] int32; ``q_positions`` [B, Sq] global positions.

    On TPU (or under ``impl='interpret'``) the pallas paged kernel walks
    each slot's live pages DIRECTLY from the physical arena — the HBM read
    per step is the slot's live tokens (page-rounded), not its whole
    ``P * page_size`` reservation, which is the decode-bandwidth lever at
    high occupancy with mixed lengths. Otherwise (``impl='dense'`` /
    ``ATT_DECODE_KERNEL=dense`` / pallas TPU absent — warn-once) the
    gather maps each slot's pages back into position order and the read is
    exactly :func:`decode_attention`'s masked-dense path: the CPU-sim
    fallback and the bit-exactness reference the kernel is asserted
    against (tests/test_decode_kernel.py).

    ``kv_quant_bits`` (8/4, with ``k_scale``/``v_scale``
    [num_pages, KVH, page_size, 1] fp32 — a small parallel scales arena
    beside the pages): the pages hold int8 payloads and the kernel
    dequantizes in-register after the quantized HBM read, so the
    live-token bandwidth win compounds with the 2-4x byte shrink. The
    gather fallback dequantizes with the reference ``dequantize_kv`` —
    identical quantized inputs produce the oracle's exact values.
    """
    mode = resolve_decode_kernel(impl)
    if kv_quant_bits and (k_scale is None or v_scale is None):
        raise ValueError("kv_quant_bits needs k_scale and v_scale")
    if mode != "dense":
        sq, d = q.shape[2], q.shape[3]
        use, interpret = _decode_kernel_gate(
            mode, sq, d, k_pages.shape[2], kv_quant_bits
        )
        if use:
            scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
            pos = _positions_2d(q_positions, q.shape[0])
            return _paged_decode_kernel_call(
                q, k_pages, v_pages, page_table, pos, scale, interpret,
                k_scale=k_scale, v_scale=v_scale, quant_bits=kv_quant_bits,
            )
    k_full = gather_kv_pages(k_pages, page_table)
    v_full = gather_kv_pages(v_pages, page_table)
    if kv_quant_bits:
        return decode_attention(
            q, k_full, v_full, q_positions=q_positions, sm_scale=sm_scale,
            impl="dense",
            k_scale=gather_kv_pages(k_scale, page_table),
            v_scale=gather_kv_pages(v_scale, page_table),
            kv_quant_bits=kv_quant_bits,
        )
    return decode_attention(
        q, k_full, v_full, q_positions=q_positions, sm_scale=sm_scale, impl="dense"
    )


# ---------------------------------------------------------------------------
# pallas ragged prefill kernel over the paged arena (ROADMAP item 3)
#
# The chunked dense prefill path pads every admission tail to a bucket,
# gathers the slot's whole arena reservation into a dense view, attends,
# and scatters the view back — per chunk. This kernel is the prefill
# counterpart of the decode kernel above: ONE dispatch packs the fresh
# tails of every pending admission into a fixed token capacity (rows are
# (token, query-head-group) pairs; padding is only up to the token-block
# granule, not a bucket), a scalar-prefetched per-block (slot, history)
# map drives the page-table walk, and the kv sweep per token block is
#
#   [arena pages 0 .. ceil(hist/page)) → packed fresh blocks 0 .. i]
#
# with flash online softmax across both phases. Prefix-aware skipping is
# structural: positions already served by a prefix-cache / tier hit are
# never re-attended as QUERIES (only the fresh tail packs rows), and the
# kv walk visits exactly the slot's live prefix pages — blocks past
# ``ceil(hist/page)`` and fresh blocks of other slots (or causally-later
# blocks of the same slot) are clamped in the index map and skipped by
# ``pl.when``, so an elided block costs neither DMA nor compute.
# Quantize-on-write is fused: the kernel quantizes each fresh K/V block
# in-register (the exact ``utils.quantization.quantize_kv`` op
# sequence), emits payload+scale outputs for the caller's single arena
# scatter, and attends the tail over the DEQUANTIZED values — the same
# read the cache serves later, so packed prefill stays bit-compatible
# with the chunked dense oracle.
# ---------------------------------------------------------------------------

_PREFILL_KERNEL_MODES = ("ragged", "dense", "interpret")
# default q token block: one sublane tile; the packer pads each tail to
# this granule (vs a whole prefill bucket on the chunked path)
_PREFILL_TOKEN_BLOCK = 8


def resolve_prefill_kernel(impl: Optional[str] = None) -> str:
    """Resolve the prefill-attention implementation choice: the explicit
    ``impl`` (``DecoderConfig.prefill_kernel``) wins, else the
    ``ATT_PREFILL_KERNEL`` env knob, else ``"ragged"`` (the packed pallas
    kernel, with a warn-once chunked-dense fallback off-TPU).
    ``"interpret"`` runs the same kernel through the pallas interpreter —
    the CPU test/CI mode, so tier-1 asserts the identical kernel."""
    mode = impl or os.environ.get("ATT_PREFILL_KERNEL", "ragged")
    if mode not in _PREFILL_KERNEL_MODES:
        raise ValueError(
            f"ATT_PREFILL_KERNEL/prefill_kernel must be one of "
            f"{_PREFILL_KERNEL_MODES}, got {mode!r}"
        )
    return mode


def _warn_prefill_fallback(reason: str):
    """Warn-once per distinct reason: the ragged prefill kernel was
    requested (or defaulted) but this process resolves to the chunked
    dense prefill path — admissions pay bucket padding and the per-chunk
    gather/scatter round-trip."""
    _warn_once(
        "prefill:" + reason,
        "ragged prefill kernel unavailable (%s); admissions resolve to "
        "the chunked dense prefill path — TTFT pays bucket padding and a "
        "gather/scatter round-trip per chunk. Set ATT_PREFILL_KERNEL="
        "dense (or DecoderConfig.prefill_kernel='dense') to silence, or "
        "'interpret' to run the kernel through the pallas interpreter.",
        reason,
    )


def _prefill_kernel_gate(mode: str, d: int, ps: int, bt: int,
                         quant_bits: int = 0):
    """(use_kernel, interpret) for one ragged prefill dispatch. Shape
    rules mirror the decode gate: head_dim a 64-multiple compiled (64
    lane-pads as a narrow tile), page size and token block 8-multiples
    (sublane tiles), int4 payload width ``d // 2`` itself a 64-multiple
    (head_dim a 128-multiple)."""
    if mode == "dense":
        return False, False
    if ps <= 0 or bt <= 0:
        _warn_prefill_fallback("no valid page/token block size")
        return False, False
    if not _has_pltpu():
        _warn_prefill_fallback("pallas TPU support missing from this jaxlib")
        return False, False
    if mode == "interpret":
        return True, True
    if jax.default_backend() != "tpu":
        _warn_prefill_fallback(f"no TPU backend ({jax.default_backend()} process)")
        return False, False
    if d % 64 != 0 or ps % 8 != 0 or bt % 8 != 0:
        _warn_prefill_fallback(
            f"shape gate: head_dim {d} must be a 64-multiple and the page "
            f"size {ps} / token block {bt} 8-multiples for the compiled "
            "kernel; admissions resolve to the chunked dense prefill path"
        )
        return False, False
    if quant_bits == 4 and (d // 2) % 64 != 0:
        _warn_prefill_fallback(
            f"shape gate: int4 KV packs the payload to head_dim/2 = "
            f"{d // 2}, which must itself be a 64-multiple for the "
            "compiled kernel (head_dim a 128-multiple); admissions "
            "resolve to the chunked dense prefill path"
        )
        return False, False
    return True, False


def prefill_kernel_active(config) -> bool:
    """Would a packed ragged prefill dispatch on a model with this config
    run the pallas kernel in this process? The serving engine's admission
    planner keys its SHAPE of work off this (packed ragged dispatch vs
    per-slot bucket chunks) and bench/telemetry use it to decide whether
    a dispatch bills the ``ragged_prefill_kernel`` roofline row — it must
    mirror :func:`ragged_prefill_attention`'s gate exactly."""
    page_size = getattr(config, "kv_page_size", None)
    if not page_size:
        return False
    mode = resolve_prefill_kernel(getattr(config, "prefill_kernel", None))
    if mode == "dense":
        return False
    bt = int(getattr(config, "prefill_kernel_block", None)
             or _PREFILL_TOKEN_BLOCK)
    quant_bits = {"int8": 8, "int4": 4}.get(
        getattr(config, "kv_cache_dtype", "bf16"), 0
    )
    use, _ = _prefill_kernel_gate(
        mode, int(getattr(config, "head_dim", 0) or 0), int(page_size), bt,
        quant_bits,
    )
    return use


def _quantize_block(x, bits):
    """In-register quantize-on-write on one [rows, D] block: the EXACT
    ``utils.quantization.quantize_kv`` op sequence (symmetric per-row
    scale over D; int4 packs value pairs low-nibble-first). Returns
    (payload int8 [rows, D or D/2], scale fp32 [rows, 1], deq fp32
    [rows, D] — exactly what ``dequantize_kv`` hands a reader, so the
    tail attends the same values the cache serves later)."""
    qmax = (1 << (bits - 1)) - 1
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    qf = jnp.clip(jnp.round(x32 / scale), -qmax, qmax)
    q = qf.astype(jnp.int8)
    deq = qf * scale
    if bits == 4:
        r, dd = q.shape
        pairs = q.reshape(r, dd // 2, 2)
        payload = (pairs[:, :, 0] & 0x0F) | ((pairs[:, :, 1] & 0x0F) << 4)
    else:
        payload = q
    return payload, scale, deq


def _prefill_kernel_body(bslot_ref, bhist_ref, tbl_ref, q_ref, k_ref, v_ref,
                         kn_ref, vn_ref, qpos_ref, kvpos_ref, o_ref,
                         acc, m_scr, l_scr, *, sm_scale, ps, bt, group,
                         npb, ntb, quant_bits=0, out_dtype=None,
                         ks_ref=None, vs_ref=None, kq_ref=None, kso_ref=None,
                         vq_ref=None, vso_ref=None):
    """One (token-block i, kv-head h, kv-step j) cell of the ragged
    prefill grid. j < ``npb`` walks the q block's slot's live arena pages
    (the prefix already in the cache — dequantized in-register when the
    arena is quantized); j >= ``npb`` walks the packed FRESH kv blocks,
    attending only blocks of the same slot at causally-visible packed
    positions. Fresh K/V is quantized in-register (quantize-on-write) —
    payload+scale outputs are written every cell their output window
    points at (identical values each visit, so revisits are benign) and
    the tail attends the dequantized form, keeping bit-compatibility
    with the chunked dense oracle that reads the cache back."""
    i, j = pl.program_id(0), pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    slot = bslot_ref[i]
    hist = bhist_ref[i]
    n_hist_blocks = (hist + ps - 1) // ps
    # per-row (token, head-group) query positions: row r is token r//group
    qpos = qpos_ref[0, 0]  # [bt]
    rowpos = jnp.broadcast_to(
        qpos.reshape(bt, 1), (bt, group)
    ).reshape(bt * group, 1)

    # fresh K/V of the block this cell's fresh window points at (clamped
    # to block 0 during the arena phase): quantize-on-write runs every
    # cell so every visited output window holds the correct payload
    kn = kn_ref[0, 0]
    vn = vn_ref[0, 0]
    if quant_bits:
        kp, ksv, kdq = _quantize_block(kn, quant_bits)
        vp, vsv, vdq = _quantize_block(vn, quant_bits)
        kq_ref[0, 0] = kp
        kso_ref[0, 0] = ksv
        vq_ref[0, 0] = vp
        vso_ref[0, 0] = vsv
        k_fresh = kdq.astype(out_dtype)
        v_fresh = vdq.astype(out_dtype)
    else:
        k_fresh, v_fresh = kn, vn

    def _accumulate(s, valid, v):
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        # a FULLY-masked row (a pad row, or a tail row in a skipped-slot
        # block) keeps m_next = NEG_INF, where exp(s - m_next) is 1, not
        # 0 — zero masked entries explicitly so its l stays 0 and the
        # safe_l output is exactly 0 (partially-masked rows already
        # underflow to 0 at the exp)
        p = jnp.where(valid, jnp.exp(s - m_next), 0.0)
        l_scr[...] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape
        )
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)

    q = q_ref[0, 0]  # [bt*group, D]

    @pl.when((slot >= 0) & (j < n_hist_blocks))
    def _arena_phase():
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if quant_bits:
            from ..utils.quantization import dequantize_kv

            k = dequantize_kv(k, ks_ref[0, 0], quant_bits, out_dtype)
            v = dequantize_kv(v, vs_ref[0, 0], quant_bits, out_dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        kvp = j * ps + jax.lax.broadcasted_iota(
            jnp.int32, (bt * group, ps), 1
        )
        # kvp < hist: only the slot's live prefix (stale arena rows past
        # the frontier never score); kvp <= rowpos masks pad rows
        valid = (kvp < hist) & (kvp <= rowpos)
        s = jnp.where(valid, s, NEG_INF)
        _accumulate(s, valid, v)

    jf = j - npb
    kslot = bslot_ref[jnp.clip(jf, 0, ntb - 1)]

    @pl.when((slot >= 0) & (j >= npb) & (kslot == slot) & (jf <= i))
    def _fresh_phase():
        # packed tails are position-ordered per slot, so blocks of the
        # same slot after this q block (jf > i) are entirely above the
        # causal frontier — skipped at block level; the per-element mask
        # below would zero them anyway
        kvq = kvpos_ref[0, 0].reshape(1, bt)  # [1, bt] fresh positions
        s = jax.lax.dot_general(
            q, k_fresh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        valid = (kvq >= 0) & (kvq <= rowpos)
        s = jnp.where(valid, s, NEG_INF)
        _accumulate(s, valid, v_fresh)

    @pl.when(j == nj - 1)
    def _out():
        l = l_scr[...][:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[...] / safe_l).astype(o_ref.dtype)


def _prefill_quant_kernel_entry(bslot_ref, bhist_ref, tbl_ref, q_ref, k_ref,
                                v_ref, ks_ref, vs_ref, kn_ref, vn_ref,
                                qpos_ref, kvpos_ref, o_ref, kq_ref, kso_ref,
                                vq_ref, vso_ref, acc, m_scr, l_scr, **kw):
    _prefill_kernel_body(bslot_ref, bhist_ref, tbl_ref, q_ref, k_ref, v_ref,
                         kn_ref, vn_ref, qpos_ref, kvpos_ref, o_ref,
                         acc, m_scr, l_scr, ks_ref=ks_ref, vs_ref=vs_ref,
                         kq_ref=kq_ref, kso_ref=kso_ref, vq_ref=vq_ref,
                         vso_ref=vso_ref, **kw)


def _ragged_prefill_kernel_call(q, k_new, v_new, k_pages, v_pages, page_table,
                                row_slot, row_pos, slot_hist, sm_scale, bt,
                                interpret, k_scale=None, v_scale=None,
                                quant_bits=0):
    _, h, cap, d = q.shape
    _, kvh, ps, pd = k_pages.shape
    group = h // kvh
    ntb = cap // bt
    g = bt * group
    npb = page_table.shape[1]
    # fold: per kv head, one [bt*group, D] block per token block, rows
    # ordered (token, group member) — same convention as _fold_q_heads
    q_r = (q[0].reshape(kvh, group, cap, d)
           .transpose(0, 2, 1, 3).reshape(kvh, ntb, g, d))
    kn_r = k_new[0].reshape(kvh, ntb, bt, d)
    vn_r = v_new[0].reshape(kvh, ntb, bt, d)
    blk_slot = row_slot.reshape(ntb, bt)[:, 0].astype(jnp.int32)
    blk_hist = jnp.where(
        blk_slot >= 0, slot_hist[jnp.maximum(blk_slot, 0)], 0
    ).astype(jnp.int32)
    pos_in = row_pos.reshape(ntb, 1, bt).astype(jnp.int32)

    entry = _prefill_quant_kernel_entry if quant_bits else _prefill_kernel_body
    kernel = functools.partial(
        entry, sm_scale=sm_scale, ps=ps, bt=bt, group=group, npb=npb,
        ntb=ntb, quant_bits=quant_bits, out_dtype=q.dtype,
    )

    def _page_spec(width):
        # arena phase: walk the q block's slot's live prefix pages; dead
        # steps (past ceil(hist/ps), or the whole fresh phase) re-address
        # the last live page so their fetch is elided
        return pl.BlockSpec(
            (1, 1, ps, width),
            lambda i, h_, j, bs, bh, tb: (
                tb[jnp.maximum(bs[i], 0),
                   jnp.clip(j, 0, jnp.maximum((bh[i] + ps - 1) // ps - 1, 0))],
                h_, 0, 0,
            ),
        )

    def _fresh_spec(width):
        # fresh phase: packed kv block j - npb (clamped to 0 during the
        # arena phase — its window doubles as the quantize-on-write
        # target, so it must always point at a real block)
        return pl.BlockSpec(
            (1, 1, bt, width),
            lambda i, h_, j, bs, bh, tb: (h_, jnp.clip(j - npb, 0, ntb - 1), 0, 0),
        )

    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda i, h_, j, bs, bh, tb: (h_, i, 0, 0)),
        _page_spec(pd),
        _page_spec(pd),
    ]
    operands = [q_r, k_pages, v_pages]
    if quant_bits:
        in_specs += [_page_spec(1), _page_spec(1)]
        operands += [k_scale, v_scale]
    in_specs += [
        _fresh_spec(d),
        _fresh_spec(d),
        pl.BlockSpec((1, 1, bt), lambda i, h_, j, bs, bh, tb: (i, 0, 0)),
        pl.BlockSpec((1, 1, bt),
                     lambda i, h_, j, bs, bh, tb: (jnp.clip(j - npb, 0, ntb - 1), 0, 0)),
    ]
    operands += [kn_r, vn_r, pos_in, pos_in]

    out_specs = [
        pl.BlockSpec((1, 1, g, d), lambda i, h_, j, bs, bh, tb: (h_, i, 0, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((kvh, ntb, g, d), q.dtype)]
    if quant_bits:
        for width, dt in ((pd, jnp.int8), (1, jnp.float32),
                          (pd, jnp.int8), (1, jnp.float32)):
            out_specs.append(_fresh_spec(width))
            out_shape.append(jax.ShapeDtypeStruct((kvh, ntb, bt, width), dt))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(ntb, kvh, npb + ntb),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[_vmem((g, d)), _vmem((g, 128)), _vmem((g, 128))],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # token blocks revisit the quantize-on-write output windows, so
        # the grid's outer dim must stay sequential ("arbitrary")
        **_grid_params(interpret, ("arbitrary", "parallel", "arbitrary")),
    )(blk_slot, blk_hist, page_table.astype(jnp.int32), *operands)
    o = outs[0]  # out_shape is a list, so pallas returns a list
    out = (o.reshape(kvh, ntb, bt, group, d)
           .transpose(0, 3, 1, 2, 4).reshape(1, h, cap, d))
    if quant_bits:
        k_pay = jnp.swapaxes(outs[1].reshape(kvh, cap, pd), 0, 1)
        k_scl = jnp.swapaxes(outs[2].reshape(kvh, cap, 1), 0, 1)
        v_pay = jnp.swapaxes(outs[3].reshape(kvh, cap, pd), 0, 1)
        v_scl = jnp.swapaxes(outs[4].reshape(kvh, cap, 1), 0, 1)
    else:
        k_pay = jnp.swapaxes(k_new[0], 0, 1)
        v_pay = jnp.swapaxes(v_new[0], 0, 1)
        k_scl = v_scl = None
    return out, k_pay, k_scl, v_pay, v_scl


def _ragged_prefill_reference(q, k_new, v_new, k_pages, v_pages, page_table,
                              row_slot, row_pos, slot_hist, scale,
                              k_scale=None, v_scale=None, quant_bits=0):
    """Chunked-dense-oracle math for a packed ragged dispatch: per-row
    gathered arena context + packed fresh kv, masked exactly as the
    kernel masks, through the reference op sequence (``quantize_kv`` /
    ``dequantize_kv`` / fp32 softmax). The fallback path and the
    bit-exactness reference the kernel is asserted against."""
    from ..utils.quantization import dequantize_kv, quantize_kv

    _, h, cap, d = q.shape
    kvh = k_pages.shape[1]
    group = h // kvh
    kn_t = jnp.swapaxes(k_new[0], 0, 1)  # [CAP, KVH, D]
    vn_t = jnp.swapaxes(v_new[0], 0, 1)
    if quant_bits:
        k_pay, k_scl = quantize_kv(kn_t, quant_bits)
        v_pay, v_scl = quantize_kv(vn_t, quant_bits)
        k_fresh = dequantize_kv(k_pay, k_scl, quant_bits, q.dtype)
        v_fresh = dequantize_kv(v_pay, v_scl, quant_bits, q.dtype)
    else:
        k_pay, v_pay = kn_t, vn_t
        k_scl = v_scl = None
        k_fresh, v_fresh = kn_t, vn_t
    k_ctx = gather_kv_pages(k_pages, page_table)  # [S, KVH, L, pd]
    v_ctx = gather_kv_pages(v_pages, page_table)
    if quant_bits:
        k_ctx = dequantize_kv(
            k_ctx, gather_kv_pages(k_scale, page_table), quant_bits, q.dtype)
        v_ctx = dequantize_kv(
            v_ctx, gather_kv_pages(v_scale, page_table), quant_bits, q.dtype)
    sl = jnp.maximum(row_slot, 0)
    k_row = k_ctx[sl]  # [CAP, KVH, L, D] — per-row slot context
    v_row = v_ctx[sl]
    qg = q[0].reshape(kvh, group, cap, d)
    s_ctx = jnp.einsum(
        "kgrd,rkld->kgrl", qg, k_row, preferred_element_type=jnp.float32
    ) * scale
    length = k_row.shape[2]
    lpos = jnp.arange(length)
    hist_r = jnp.where(row_slot >= 0, slot_hist[sl], 0)
    valid_ctx = ((lpos[None, :] < hist_r[:, None])
                 & (lpos[None, :] <= row_pos[:, None]))
    s_ctx = jnp.where(valid_ctx[None, None], s_ctx, NEG_INF)
    kf = jnp.swapaxes(k_fresh, 0, 1)  # [KVH, CAP, D]
    vf = jnp.swapaxes(v_fresh, 0, 1)
    s_new = jnp.einsum(
        "kgrd,kcd->kgrc", qg, kf, preferred_element_type=jnp.float32
    ) * scale
    valid_new = ((row_slot[None, :] == row_slot[:, None])
                 & (row_slot[:, None] >= 0)
                 & (row_pos[None, :] <= row_pos[:, None])
                 & (row_pos[None, :] >= 0))
    s_new = jnp.where(valid_new[None, None], s_new, NEG_INF)
    s = jnp.concatenate([s_ctx, s_new], axis=-1)
    p = jax.nn.softmax(s, axis=-1)
    out = (jnp.einsum("kgrl,rkld->kgrd", p[..., :length].astype(v_row.dtype), v_row)
           + jnp.einsum("kgrc,kcd->kgrd", p[..., length:].astype(vf.dtype), vf))
    # pad rows are fully masked: softmax degenerates to uniform — force
    # the kernel's exact 0 output (safe_l semantics) instead
    row_ok = (row_slot >= 0) & (row_pos >= 0)
    out = jnp.where(row_ok[None, None, :, None], out, 0.0)
    out = out.reshape(h, cap, d)[None].astype(q.dtype)
    return out, k_pay, k_scl, v_pay, v_scl


def ragged_prefill_attention(
    q: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    *,
    page_table: jax.Array,
    row_slot: jax.Array,
    row_pos: jax.Array,
    slot_hist: jax.Array,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
    token_block: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    kv_quant_bits: int = 0,
):
    """Packed ragged prefill attention over the paged KV arena, with
    quantize-on-write fused.

    q/k_new/v_new: [1, H|KVH, CAP, D] — the packed fresh tails of every
    admission in this dispatch (post-RoPE), CAP a fixed compile-time
    capacity. ``row_slot``/``row_pos`` [CAP] int32 map each packed row to
    its (slot, absolute position); -1 marks padding (only up to the
    token-block granule). Rows of one slot must be contiguous,
    position-ordered, and token-block aligned — the packer's contract.
    ``slot_hist`` [S] int32 is each slot's live prefix length (tokens
    already in the arena: a prefix-cache/tier hit plus earlier packed
    dispatches of a long tail); the kernel walks exactly
    ``ceil(hist/page_size)`` arena pages per token block and never
    re-attends served positions as queries — the prefix-aware skip.

    Returns ``(out [1, H, CAP, D], k_payload, k_scale, v_payload,
    v_scale)`` — payloads token-major [CAP, KVH, pd] ready for one arena
    scatter (scales None unquantized; payloads then pass through k_new/
    v_new). Dispatch mirrors the decode kernel's:
    :func:`resolve_prefill_kernel` (``impl`` / ``ATT_PREFILL_KERNEL``,
    default "ragged" with a warn-once dense fallback off-TPU,
    "interpret" for CPU tests); the chunked-dense reference stays the
    bit-exactness oracle."""
    mode = resolve_prefill_kernel(impl)
    b, h, cap, d = q.shape
    if b != 1:
        raise ValueError(f"packed ragged prefill takes batch 1, got {b}")
    bt = int(token_block or _PREFILL_TOKEN_BLOCK)
    if cap % bt:
        raise ValueError(
            f"packed capacity {cap} must be a multiple of the token "
            f"block {bt}"
        )
    if kv_quant_bits and (k_scale is None or v_scale is None):
        raise ValueError("kv_quant_bits needs k_scale and v_scale")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    row_slot = jnp.asarray(row_slot, jnp.int32)
    row_pos = jnp.asarray(row_pos, jnp.int32)
    slot_hist = jnp.asarray(slot_hist, jnp.int32)
    if mode != "dense":
        use, interpret = _prefill_kernel_gate(
            mode, d, k_pages.shape[2], bt, kv_quant_bits
        )
        if use:
            return _ragged_prefill_kernel_call(
                q, k_new, v_new, k_pages, v_pages, page_table, row_slot,
                row_pos, slot_hist, scale, bt, interpret,
                k_scale=k_scale, v_scale=v_scale, quant_bits=kv_quant_bits,
            )
    return _ragged_prefill_reference(
        q, k_new, v_new, k_pages, v_pages, page_table, row_slot, row_pos,
        slot_hist, scale, k_scale=k_scale, v_scale=v_scale,
        quant_bits=kv_quant_bits,
    )


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Attention dispatcher: pallas flash kernel on TPU when shapes allow,
    XLA reference otherwise. Layout [B, H, S, D]. ``impl`` ∈
    {"auto", "flash", "xla"}.

    Padding should arrive as ``kv_mask`` and packed sequences as
    ``segment_ids`` — both stay on the flash path. An arbitrary additive
    ``bias`` falls back to XLA (the kernel implements masks, not biases)."""
    if impl == "flash" and bias is not None:
        raise ValueError("flash impl does not support arbitrary bias; use kv_mask/segment_ids or impl='xla'")

    def _fold_masks_into_bias(bias):
        # Masks must survive on every path — the XLA fallback honors them by
        # folding into the additive bias (padding keys get -inf logits).
        if kv_mask is None and q_segment_ids is None:
            return bias
        bias_parts = [] if bias is None else [bias]
        if kv_mask is not None:
            bias_parts.append(jnp.where(kv_mask[:, None, None, :] != 0, 0.0, NEG_INF))
        if q_segment_ids is not None:
            same = q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
            bias_parts.append(jnp.where(same, 0.0, NEG_INF))
        return sum(bias_parts)

    if impl == "xla" or bias is not None:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale, bias=_fold_masks_into_bias(bias))
    on_tpu = jax.default_backend() == "tpu"
    blocks_ok = (
        _pick_block(q.shape[2], 1024) and _pick_block(k.shape[2], 1024) and q.shape[-1] % 128 == 0
    )
    if impl == "flash" or (impl == "auto" and (on_tpu or interpret) and blocks_ok):
        return flash_attention(
            q, k, v, causal=causal, sm_scale=sm_scale,
            kv_mask=kv_mask, q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            interpret=interpret or not on_tpu,
        )
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale, bias=_fold_masks_into_bias(bias))
