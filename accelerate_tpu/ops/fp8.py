"""fp8 matmul path (scaled e4m3 forward / e5m2 backward).

Parity target: the reference's fp8 capability via TransformerEngine
(/root/reference/src/accelerate/utils/transformer_engine.py:27-130 swaps
torch Linears for te.Linear under an fp8 recipe) and MS-AMP
(accelerator.py:1992-2027). The TPU-native design needs no layer swapping:
``fp8_dot`` is a drop-in contraction the models call when
``use_fp8`` is on, implementing the standard recipe —

- forward operands quantize to float8_e4m3 with per-tensor current scaling
  (amax / dtype-max), accumulate in fp32 on the MXU;
- gradients quantize to float8_e5m2 (wider exponent: grads are
  scale-volatile) via a custom VJP;
- scales are fp32 scalars computed on the fly ("current scaling" — the
  delayed-scaling history of TE trades accuracy for a reduction it only
  needs because torch can't fuse the amax; XLA fuses the reduction into the
  producer for free).

On hardware without fp8 MXU support (v5e and older), XLA emulates via
convert — numerics are exercised everywhere, speedups arrive on v6e+.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def _amax_scale(x, fmax) -> jax.Array:
    """fp32 scale mapping x's current amax to the fp8 dtype's max."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.where(amax > 0, amax / fmax, 1.0)


def quantize_fp8(x, dtype=jnp.float8_e4m3fn, fmax: float = E4M3_MAX):
    """(q, scale): q = clip(x / scale) in fp8, x ~= q * scale."""
    scale = _amax_scale(x, fmax)
    q = jnp.clip(x.astype(jnp.float32) / scale, -fmax, fmax).astype(dtype)
    return q, scale


def _scaled_dot(a, b, a_dtype, a_max, b_dtype, b_max, out_dtype):
    qa, sa = quantize_fp8(a, a_dtype, a_max)
    qb, sb = quantize_fp8(b, b_dtype, b_max)
    out = jax.lax.dot_general(
        qa, qb, (((a.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return (out * (sa * sb)).astype(out_dtype)


@jax.custom_vjp
def fp8_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """a [..., K] @ b [K, N] with e4m3 forward operands, fp32 accumulation,
    e5m2 gradient operands. Output dtype follows ``a``."""
    return _scaled_dot(a, b, jnp.float8_e4m3fn, E4M3_MAX, jnp.float8_e4m3fn, E4M3_MAX, a.dtype)


def _fp8_dot_fwd(a, b):
    return fp8_dot(a, b), (a, b)


def _fp8_dot_bwd(res, g):
    a, b = res
    # da = g @ b.T ; db = a.T @ g — gradients ride e5m2, weights/acts e4m3
    da = _scaled_dot(g, b.T, jnp.float8_e5m2, E5M2_MAX, jnp.float8_e4m3fn, E4M3_MAX, a.dtype)
    a2 = a.reshape(-1, a.shape[-1])
    g2 = g.reshape(-1, g.shape[-1])
    db = _scaled_dot(a2.T, g2, jnp.float8_e4m3fn, E4M3_MAX, jnp.float8_e5m2, E5M2_MAX, b.dtype)
    return da.reshape(a.shape), db


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def maybe_fp8_dot(a: jax.Array, b: jax.Array, use_fp8: bool):
    """Contraction the model layers call: fp8 recipe when enabled, plain
    dot otherwise (same signature, so the call site stays branch-free)."""
    if use_fp8:
        return fp8_dot(a, b)
    return a @ b
