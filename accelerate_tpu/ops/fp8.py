"""fp8 matmul path (scaled e4m3 forward / e5m2 backward).

Parity target: the reference's fp8 capability via TransformerEngine
(/root/reference/src/accelerate/utils/transformer_engine.py:27-130 swaps
torch Linears for te.Linear under an fp8 recipe) and MS-AMP
(accelerator.py:1992-2027). The TPU-native design needs no layer swapping:
``fp8_dot`` is a drop-in contraction the models call when
``use_fp8`` is on, implementing the standard recipe —

- forward operands quantize to float8_e4m3 with per-tensor current scaling
  (amax / dtype-max), accumulate in fp32 on the MXU;
- gradients quantize to float8_e5m2 (wider exponent: grads are
  scale-volatile) via a custom VJP;
- scales are fp32 scalars computed on the fly ("current scaling" — the
  delayed-scaling history of TE trades accuracy for a reduction it only
  needs because torch can't fuse the amax; XLA fuses the reduction into the
  producer for free).

On hardware without fp8 MXU support (v5e and older), XLA emulates via
convert — numerics are exercised everywhere, speedups arrive on v6e+.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def _amax_scale(x, fmax) -> jax.Array:
    """fp32 scale mapping x's current amax to the fp8 dtype's max."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.where(amax > 0, amax / fmax, 1.0)


def quantize_fp8(x, dtype=jnp.float8_e4m3fn, fmax: float = E4M3_MAX):
    """(q, scale): q = clip(x / scale) in fp8, x ~= q * scale."""
    scale = _amax_scale(x, fmax)
    q = jnp.clip(x.astype(jnp.float32) / scale, -fmax, fmax).astype(dtype)
    return q, scale


def _scaled_dot(a, b, a_dtype, a_max, b_dtype, b_max, out_dtype):
    qa, sa = quantize_fp8(a, a_dtype, a_max)
    qb, sb = quantize_fp8(b, b_dtype, b_max)
    out = jax.lax.dot_general(
        qa, qb, (((a.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return (out * (sa * sb)).astype(out_dtype)


@jax.custom_vjp
def fp8_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """a [..., K] @ b [K, N] with e4m3 forward operands, fp32 accumulation,
    e5m2 gradient operands. Output dtype follows ``a``."""
    return _scaled_dot(a, b, jnp.float8_e4m3fn, E4M3_MAX, jnp.float8_e4m3fn, E4M3_MAX, a.dtype)


def _fp8_dot_fwd(a, b):
    return fp8_dot(a, b), (a, b)


def _fp8_dot_bwd(res, g):
    a, b = res
    # da = g @ b.T ; db = a.T @ g — gradients ride e5m2, weights/acts e4m3
    da = _scaled_dot(g, b.T, jnp.float8_e5m2, E5M2_MAX, jnp.float8_e4m3fn, E4M3_MAX, a.dtype)
    a2 = a.reshape(-1, a.shape[-1])
    g2 = g.reshape(-1, g.shape[-1])
    db = _scaled_dot(a2.T, g2, jnp.float8_e4m3fn, E4M3_MAX, jnp.float8_e5m2, E5M2_MAX, b.dtype)
    return da.reshape(a.shape), db


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def maybe_fp8_dot(a: jax.Array, b: jax.Array, use_fp8: bool):
    """Contraction the model layers call: fp8 recipe when enabled, plain
    dot otherwise (same signature, so the call site stays branch-free)."""
    if use_fp8:
        return fp8_dot(a, b)
    return a @ b


# ---------------------------------------------------------------------------
# Delayed scaling (TransformerEngine DelayedScaling parity)
# ---------------------------------------------------------------------------

def init_amax_history(length: int = 16) -> jax.Array:
    """[2, H] fp32 amax history for one contraction's (a, b) operands."""
    return jnp.zeros((2, length), jnp.float32)


def _delayed_scale(hist_row, fmax, margin: float):
    """TE recipe: scale from the HISTORY's max (amax_compute_algo="max"),
    with a safety margin, falling back to 1.0 before any history exists."""
    amax = jnp.max(hist_row) * margin
    return jnp.where(amax > 0, amax / fmax, 1.0)


def _roll_in(hist_row, amax):
    return jnp.concatenate([amax[None], hist_row[:-1]])


def _record_amax(hist_row, amax):
    """Accumulate this call's amax into the CURRENT slot (element-wise max).
    The slot ADVANCES once per optimizer step (`roll_amax_histories`, called
    by the TrainEngine), not per contraction call — so pipeline schedule
    ticks and gradient-accumulation microsteps share one history slot per
    step and the window spans `fp8_amax_history_len` real steps (TE's
    per-iteration roll), instead of shrinking by the microbatch factor."""
    return hist_row.at[0].set(jnp.maximum(hist_row[0], amax))


def roll_amax_histories(stats_tree):
    """Advance every amax history one step: shift the slots, zero the new
    current slot (a zero slot contributes nothing to the max-over-history
    scale). Leaves are [..., 2, H]; works under layer-scan and
    pipeline-stage leading dims alike. The TrainEngine calls this once per
    optimizer step when an "fp8_stats" collection is live."""

    def _one(leaf):
        return jnp.concatenate(
            [jnp.zeros_like(leaf[..., :1]), leaf[..., :-1]], axis=-1
        )

    return jax.tree_util.tree_map(_one, stats_tree)


def fp8_dot_delayed(a: jax.Array, b: jax.Array, hist: jax.Array, margin: float = 1.0):
    """``a [..., K] @ b [K, N]`` under the DELAYED-scaling fp8 recipe
    (reference utils/transformer_engine.py:96-130 builds exactly this TE
    recipe): forward operands quantize with scales derived from the amax
    HISTORY of previous steps, not the current tensor; this call's amaxes
    max-accumulate into the history's current slot (the slot advances once
    per optimizer step — `roll_amax_histories`). Returns ``(out, new_hist)``.

    Current scaling (``fp8_dot``) is usually the better default on TPU —
    XLA fuses the amax reduction into the producer, so the "extra pass"
    delayed scaling exists to avoid is already free. Delayed scaling remains
    the recipe of record for TE parity and for workloads whose activation
    ranges spike transiently (the history's max rides over one-step
    outliers instead of letting them crush the scale). Gradients keep
    current e5m2 scaling, like the forward-history-only deployments of TE.
    """
    sa = _delayed_scale(hist[0], E4M3_MAX, margin)
    sb = _delayed_scale(hist[1], E4M3_MAX, margin)
    new_hist = jnp.stack([
        _record_amax(hist[0], jnp.max(jnp.abs(a.astype(jnp.float32)))),
        _record_amax(hist[1], jnp.max(jnp.abs(b.astype(jnp.float32)))),
    ])
    out = _fp8_dot_with_scales(a, b, sa, sb)
    return out, new_hist


@jax.custom_vjp
def _fp8_dot_with_scales(a, b, sa, sb):
    qa = jnp.clip(a.astype(jnp.float32) / sa, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
    qb = jnp.clip(b.astype(jnp.float32) / sb, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
    out = jax.lax.dot_general(
        qa, qb, (((a.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return (out * (sa * sb)).astype(a.dtype)


def _fp8_scales_fwd(a, b, sa, sb):
    return _fp8_dot_with_scales(a, b, sa, sb), (a, b)


def _fp8_scales_bwd(res, g):
    # same gradient recipe as the current-scaling path — one implementation
    da, db = _fp8_dot_bwd(res, g)
    return da, db, None, None


_fp8_dot_with_scales.defvjp(_fp8_scales_fwd, _fp8_scales_bwd)


def fp8_attn_proj(module, name: str, x, w, num_heads: int, head_dim: int, cfg):
    """Attention input projection under the fp8 recipe: ``x [b, s, e] @
    w [e, nh, d]`` as a 2D fp8 contraction, returned in [b, nh, s, d]
    layout (TE parity — the reference converter swaps every Linear incl.
    QKV, transformer_engine.py:38-52). One implementation shared by the
    decoder, encoder, and seq2seq attention blocks."""
    e = w.shape[0]
    b, s = x.shape[0], x.shape[1]
    out2 = module_fp8_dot(module, name, x, w.reshape(e, num_heads * head_dim), cfg)
    return out2.reshape(b, s, num_heads, head_dim).transpose(0, 2, 1, 3)


def fp8_attn_out(module, name: str, attn, w, cfg):
    """Attention output projection under fp8: ``attn [b, h, s, d] @
    w [h, d, e]`` -> [b, s, e]."""
    b, h, s, d = attn.shape
    a2 = attn.transpose(0, 2, 1, 3).reshape(b, s, h * d)
    return module_fp8_dot(module, name, a2, w.reshape(h * d, w.shape[-1]), cfg)


_delayed_fallback_warned = False


def _warn_delayed_fallback_once():
    """The delayed recipe was requested but this apply is running current
    scaling — different numerics than the config states deserve one loud
    notice (round-4 review: the quiet fallback hid the recipe swap). Two
    ways to get here, both covered by the message: the model was init'd
    without the recipe (the stats collection never existed), or this
    PARTICULAR apply didn't receive the collection — e.g. inference/
    generation passing only {'params': ...}, where history-free current
    scaling is the normal and correct behavior."""
    global _delayed_fallback_warned
    if _delayed_fallback_warned:
        return
    _delayed_fallback_warned = True
    import warnings

    warnings.warn(
        "fp8_recipe='delayed' is configured but this apply has no "
        "'fp8_stats' collection, so CURRENT scaling is used for it. If "
        "this is inference/generation (apply with only {'params': ...}), "
        "that is expected — the amax history is a training-time state. If "
        "this is training, init the model with use_fp8=True and "
        "fp8_recipe='delayed' so the history variables exist.",
        stacklevel=3,
    )


def module_fp8_dot(module, name: str, a: jax.Array, b: jax.Array, cfg):
    """The contraction call for flax modules with a config carrying
    ``use_fp8`` / ``fp8_recipe`` / ``fp8_amax_history_len``: plain dot when
    off, current-scaling fp8 by default, or delayed scaling with the amax
    history threaded through the module's "fp8_stats" collection (rides the
    TrainEngine's mutable extra state like BatchNorm statistics do)."""
    if not getattr(cfg, "use_fp8", False):
        return a @ b
    if getattr(cfg, "fp8_recipe", "current") != "delayed":
        return fp8_dot(a, b)
    if not (
        module.has_variable("fp8_stats", name)
        or module.is_mutable_collection("fp8_stats")
        or module.is_initializing()
    ):
        # delayed recipe requested but the stats collection was never
        # initialized (e.g. the model was init'd with use_fp8=False and
        # Accelerator(mixed_precision="fp8") flipped it afterwards): fall
        # back to current scaling rather than failing — to get the history,
        # set use_fp8=True + fp8_recipe="delayed" in the config BEFORE init.
        _warn_delayed_fallback_once()
        return fp8_dot(a, b)
    hist = module.variable(
        "fp8_stats", name,
        lambda: init_amax_history(getattr(cfg, "fp8_amax_history_len", 16)),
    )
    out, new_hist = fp8_dot_delayed(a, b, hist.value)
    if module.is_mutable_collection("fp8_stats"):
        hist.value = new_hist
    return out
