"""Loss functions tuned for the TPU memory budget.

The LM-head logits tensor [B*S, V] in fp32 is routinely the single largest
activation in decoder training (for a 32k-vocab model at 8k context it
exceeds the whole transformer's activations). ``fused_linear_cross_entropy``
never materializes it: the hidden states are chunked along tokens, each
chunk's ``hidden @ W_vocab`` + softmax-CE is computed inside a
``jax.checkpoint`` region of a ``lax.scan``, so the backward pass recomputes
each chunk's logits instead of storing them. Same trade XLA can't make on
its own (it won't rematerialize across the loss boundary unless told).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    *,
    ignore_index: Optional[int] = None,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Mean token CE from explicit logits [..., V] and integer labels [...].

    fp32 logsumexp regardless of logits dtype; ``ignore_index`` positions are
    masked out of the mean (HF/torch `F.cross_entropy` semantics)."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    safe_labels = labels if ignore_index is None else jnp.where(labels == ignore_index, 0, labels)
    label_logit = jnp.take_along_axis(
        logits, safe_labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = lse - label_logit
    if label_smoothing > 0.0:
        smooth = lse - jnp.mean(logits, axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    if ignore_index is not None:
        mask = (labels != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def fused_linear_cross_entropy(
    hidden: jax.Array,
    vocab_kernel: jax.Array,
    labels: jax.Array,
    *,
    ignore_index: Optional[int] = None,
    num_chunks: int = 8,
    logit_dtype=jnp.float32,
) -> jax.Array:
    """Chunked LM-head + CE that never materializes full logits.

    hidden: [N, E] (flatten batch/seq first), vocab_kernel: [E, V],
    labels: [N]. Returns the mean CE over non-ignored tokens.
    """
    n, e = hidden.shape
    if n % num_chunks:
        # fall back to fewer chunks rather than padding (static shapes)
        for c in range(min(num_chunks, n), 0, -1):
            if n % c == 0:
                num_chunks = c
                break
    chunk = n // num_chunks

    # STRIDED chunking (chunk c = rows {c, c+C, c+2C, ...}): the token dim is
    # sharded over the data axes in contiguous blocks, so the reshape must
    # split the major (sharded) dim for the per-chunk row dim to inherit the
    # sharding — a contiguous [C, chunk] split would shard the scan dim and
    # force the SPMD partitioner into full rematerialization per slice. The
    # loss is a masked mean over all rows, so the permutation is irrelevant.
    h_chunks = hidden.reshape(chunk, num_chunks, e).swapaxes(0, 1)
    l_chunks = labels.reshape(chunk, num_chunks).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_loss(h, lab):
        logits = (h @ vocab_kernel).astype(logit_dtype)
        logits = logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        safe_lab = lab if ignore_index is None else jnp.where(lab == ignore_index, 0, lab)
        label_logit = jnp.take_along_axis(
            logits, safe_lab[:, None].astype(jnp.int32), axis=-1
        )[:, 0]
        nll = lse - label_logit
        if ignore_index is not None:
            mask = (lab != ignore_index).astype(jnp.float32)
            return jnp.sum(nll * mask), jnp.sum(mask)
        return jnp.sum(nll), jnp.asarray(float(chunk))

    def body(carry, xs):
        total, count = carry
        h, lab = xs
        s, c = chunk_loss(h, lab)
        return (total + s, count + c), None

    (total, count), _ = jax.lax.scan(body, (jnp.asarray(0.0), jnp.asarray(0.0)), (h_chunks, l_chunks))
    return total / jnp.maximum(count, 1.0)
