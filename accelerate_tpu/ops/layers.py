"""Elementwise-adjacent building blocks, deliberately written as plain jnp.

XLA fuses these into the surrounding matmuls (HBM-bandwidth win comes from
fusion, not hand kernels — pallas here would *block* fusion). fp32 internal
accumulation for norms regardless of the bf16 activations around them.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with fp32 internal math, output in x.dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    """SwiGLU activation: silu(gate) * up."""
    return jax.nn.silu(gate) * up


def rotary_embedding_tables(
    positions: jax.Array,
    head_dim: int,
    *,
    theta: float = 10000.0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """(sin, cos) tables for RoPE; positions [..., S] -> [..., S, head_dim/2]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.sin(angles).astype(dtype), jnp.cos(angles).astype(dtype)


def apply_rotary_embedding(
    x: jax.Array, sin: jax.Array, cos: jax.Array
) -> jax.Array:
    """Rotate pairs (split-half convention). x: [B, H, S, D]; sin/cos
    [S, D/2] or [B, S, D/2] (broadcast over heads)."""
    dtype = x.dtype
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    if sin.ndim == 2:  # [S, half] -> broadcast over batch+heads
        sin_b = sin[None, None, :, :].astype(jnp.float32)
        cos_b = cos[None, None, :, :].astype(jnp.float32)
    else:  # [B, S, half] -> broadcast over heads
        sin_b = sin[:, None, :, :].astype(jnp.float32)
        cos_b = cos[:, None, :, :].astype(jnp.float32)
    r1 = x1 * cos_b - x2 * sin_b
    r2 = x2 * cos_b + x1 * sin_b
    return jnp.concatenate([r1, r2], axis=-1).astype(dtype)
