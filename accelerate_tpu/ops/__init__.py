"""TPU compute ops.

Design stance: pallas kernels ONLY where they beat XLA fusion (attention —
the O(S^2) memory-bound hot spot); everything elementwise-adjacent
(rmsnorm, rope, swiglu, losses) is written as plain jnp so XLA fuses it
into neighboring matmuls (SURVEY §"Design for tpu hardware": "Let XLA
fuse — don't hand-schedule what the compiler already does").
"""

from .attention import dot_product_attention, flash_attention, mha_reference
from .layers import apply_rotary_embedding, rms_norm, rotary_embedding_tables, swiglu
from .losses import fused_linear_cross_entropy, softmax_cross_entropy

__all__ = [
    "dot_product_attention",
    "flash_attention",
    "mha_reference",
    "apply_rotary_embedding",
    "rms_norm",
    "rotary_embedding_tables",
    "swiglu",
    "fused_linear_cross_entropy",
    "softmax_cross_entropy",
]
