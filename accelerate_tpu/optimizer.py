"""Optimizer wrapper (parity: /root/reference/src/accelerate/optimizer.py,
214 LoC: AcceleratedOptimizer).

The reference wraps a torch optimizer: device-places its state, skips
``step()`` during accumulation, runs the GradScaler dance, detects skipped
steps. Here the optimizer is an optax ``GradientTransformation`` and the
actual update is one fused jit (owned by the TrainEngine in accelerator.py);
this wrapper keeps the *call-site contract*: ``optimizer.step()``,
``optimizer.zero_grad()``, ``optimizer.state_dict()``,
``optimizer_step_was_skipped`` all behave like the reference.
"""

from __future__ import annotations

from typing import Any, Optional

from .state import AcceleratorState, GradientState


class AcceleratedOptimizer:
    def __init__(self, optimizer, engine=None):
        # ``optimizer`` is an optax GradientTransformation (pair of pure fns);
        # ``engine`` is wired in by Accelerator.prepare.
        self.optimizer = optimizer
        self.engine = engine
        self.accelerator_state = AcceleratorState()
        self.gradient_state = GradientState()

    @property
    def state(self):
        """Current optax state (a pytree of global jax.Arrays)."""
        if self.engine is None:
            return None
        return self.engine.opt_state

    @property
    def param_groups(self):
        """Torch-parity shim: one group exposing the current lr."""
        lr = None
        if self.engine is not None:
            lr = self.engine.current_learning_rate()
        return [{"lr": lr, "params": []}]

    def state_dict(self):
        if self.engine is None:
            return {}
        return {"opt_state": self.engine.opt_state, "step_count": self.engine.step_count}

    def load_state_dict(self, state_dict):
        if self.engine is not None:
            self.engine.load_optimizer_state(state_dict)

    def zero_grad(self, set_to_none: bool = True):
        """Reset the gradient-accumulation buffer. Gated on sync_gradients
        exactly like the reference (optimizer.py:112-122): during
        accumulation this is a no-op so grads keep accumulating."""
        if self.gradient_state.sync_gradients and self.engine is not None:
            self.engine.zero_grad()

    def step(self, closure=None):
        """Apply the fused update. Skips silently while accumulating
        (reference optimizer.py:153); with fp16 the update is conditionally
        skipped on non-finite grads inside the jit (GradScaler analog)."""
        if closure is not None:
            closure()
        if not self.gradient_state.sync_gradients:
            return
        if self.engine is None:
            raise RuntimeError(
                "This AcceleratedOptimizer is not attached to a model; pass the "
                "model and optimizer to `accelerator.prepare` together."
            )
        from .telemetry.spans import span

        with span("engine/optimizer_step", cat="engine"):
            self.engine.optimizer_step()

    def train(self):  # torch-parity no-op
        return self

    def eval(self):  # torch-parity no-op
        return self

    @property
    def step_was_skipped(self) -> bool:
        """True when the last ``step`` was skipped because of non-finite
        fp16 gradients (reference accelerator.optimizer_step_was_skipped)."""
        if self.engine is None:
            return False
        return self.engine.last_step_skipped()

    def __getstate__(self):
        return self.__dict__.copy()

    def __setstate__(self, state):
        self.__dict__.update(state)
