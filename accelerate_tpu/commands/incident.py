"""``accelerate-tpu incident`` — reconstruct incidents from artifacts.

The on-call path after an alert: ``watch --fleet`` shows the rule firing
and names exemplar requests; this command rebuilds the whole story —
``incident list <dir>`` enumerates every pending→firing→resolved window
found in the alert logs, ``incident show <dir>`` (``--index N`` /
``--rule NAME``) prints one incident's cross-plane timeline (alert
edges, replica health flaps, placement/autoscale decisions, canary
failures, flight dumps) and the stage-decomposed exemplar requests, and
``--json`` emits the raw reconstruction for tooling. Works offline from
any telemetry artifact dir or a live FleetCollector log_dir; rotated
artifact generations are read transparently.

docs/telemetry.md ("From alert to root cause in four commands") walks
the full watch → incident → trace pipeline.

Plain stdlib — no jax (declared in ``analysis/hygiene.py``): incidents
are reconstructed wherever the log files land.
"""

from __future__ import annotations

import json
import sys
import time


def _ts(t) -> str:
    if t is None:
        return "?"
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(t)))
    except (TypeError, ValueError, OverflowError):
        return "?"


def _fmt_dur(s) -> str:
    if s is None:
        return "open"
    s = float(s)
    if s < 120:
        return f"{s:.1f}s"
    return f"{s / 60:.1f}m"


def format_incident_line(inc: dict) -> str:
    ex = ",".join(str(r) for r in (inc.get("exemplars") or [])[:3]) or "-"
    return (f'#{inc["index"]}  {inc["rule"]:<22} {inc.get("state", "?"):<9}'
            f' fired={_ts(inc.get("fired_t"))}'
            f' dur={_fmt_dur(inc.get("duration_s")):<7}'
            f' events={len(inc.get("events") or []):<4} exemplars={ex}')


def format_incident(inc: dict) -> str:
    """One incident's full render: header, ordered cross-plane timeline
    (source-tagged), and the exemplar stage breakdowns."""
    lines = [
        f'incident #{inc["index"]}: {inc["rule"]} '
        f'[{inc.get("severity") or "?"}] — {inc.get("state")}',
    ]
    if inc.get("description"):
        lines.append(f'  {inc["description"]}')
    lines.append(
        f'  window: start={_ts(inc.get("start_t"))} '
        f'fired={_ts(inc.get("fired_t"))} '
        f'resolved={_ts(inc.get("resolved_t"))} '
        f'({_fmt_dur(inc.get("duration_s"))})'
    )
    if inc.get("peak_value") is not None:
        lines.append(f'  peak value: {inc["peak_value"]:.4g}')
    lines.append("")
    lines.append("  timeline:")
    for evt in inc.get("events") or []:
        lines.append(
            f'    {_ts(evt.get("t_unix_s"))}  [{evt.get("source", "?"):<9}] '
            f'{evt.get("detail", "")}'
        )
    if inc.get("events_truncated"):
        lines.append(f'    ... {inc["events_truncated"]} more events folded')
    rows = inc.get("exemplar_requests") or []
    if rows:
        lines.append("")
        lines.append("  exemplar requests:")
        for row in rows:
            if row.get("missing"):
                lines.append(
                    f'    {row["request_id"]}: no request record in this dir '
                    "(rotated away, or logged on another host)"
                )
                continue
            stages = row.get("stages") or {}
            parts = ", ".join(f"{s}={v:.1f}ms" for s, v in stages.items() if v)
            top = row.get("top_stage")
            lines.append(
                f'    {row["request_id"]} '
                f'(replica {row.get("replica") or "?"}): {parts}'
                + (f"  <- {top} dominates" if top else "")
            )
    return "\n".join(lines)


def incident_command(args) -> int:
    from ..telemetry.incidents import reconstruct_incidents, summarize_incidents

    incidents = reconstruct_incidents(args.target, pad_s=args.pad_s)
    if args.json:
        print(json.dumps({"incidents": incidents,
                          "summary": summarize_incidents(incidents)}))
        return 0
    if not incidents:
        print(f"no incidents found under {args.target} — no alert ever "
              "reached firing in alerts-*.jsonl (see docs/telemetry.md)",
              file=sys.stderr)
        return 1
    if args.action == "list":
        for inc in incidents:
            print(format_incident_line(inc))
        s = summarize_incidents(incidents)
        dur = (f', mean duration {s["mean_duration_s"]:.1f}s'
               if s.get("mean_duration_s") is not None else "")
        print(f'{s["count"]} incident(s), {s["open"]} open{dur}')
        return 0
    # show
    chosen = incidents
    if args.rule:
        chosen = [i for i in incidents if i["rule"] == args.rule]
        if not chosen:
            print(f'no incident for rule {args.rule!r}; rules seen: '
                  f'{sorted(set(i["rule"] for i in incidents))}',
                  file=sys.stderr)
            return 1
    if args.index is not None:
        chosen = [i for i in incidents if i["index"] == args.index]
        if not chosen:
            print(f"no incident #{args.index} (have 0..{len(incidents) - 1})",
                  file=sys.stderr)
            return 1
    elif not args.rule:
        chosen = [incidents[-1]]  # default: the most recent incident
    print("\n\n".join(format_incident(i) for i in chosen))
    return 0


def register(subparsers):
    parser = subparsers.add_parser(
        "incident",
        help="Reconstruct incidents from a telemetry dir: per-alert "
             "cross-plane timeline (health flaps, placements, autoscale, "
             "canary, flight dumps) + exemplar request stage breakdowns",
    )
    parser.add_argument("action", choices=("list", "show"),
                        help="list all incident windows, or show one timeline")
    parser.add_argument("target",
                        help="telemetry artifact dir (or FleetCollector "
                             "log_dir) holding alerts-*.jsonl")
    parser.add_argument("--index", type=int, default=None,
                        help="incident number from `incident list` "
                             "(default: most recent)")
    parser.add_argument("--rule", default=None,
                        help="show every incident of one alert rule")
    parser.add_argument("--pad-s", type=float, default=30.0,
                        help="seconds scanned beyond the alert window on "
                             "each side (default 30)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.set_defaults(func=incident_command)
