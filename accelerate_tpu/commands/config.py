"""`accelerate-tpu config` — questionnaire writing the default yaml
(parity: reference commands/config/{config,cluster,default}.py)."""

from __future__ import annotations

import os

from .config_args import ClusterConfig, default_config_file


def register(subparsers):
    parser = subparsers.add_parser("config", help="Create the launch config file")
    parser.add_argument("--config_file", default=None)
    sub = parser.add_subparsers(dest="config_subcommand")
    default_p = sub.add_parser("default", help="Write a non-interactive default config")
    default_p.add_argument("--config_file", default=None)
    default_p.add_argument("--mixed_precision", default="no", choices=["no", "fp16", "bf16"])
    default_p.set_defaults(func=default_command)
    update_p = sub.add_parser(
        "update", help="Rewrite an existing config with the current schema (add new fields, drop stale ones)"
    )
    update_p.add_argument("--config_file", default=None)
    update_p.set_defaults(func=update_command)
    parser.set_defaults(func=config_command)
    return parser


def _ask(question: str, default, cast=str):
    try:
        raw = input(f"{question} ({default}): ").strip()
    except EOFError:  # closed/hung-up stdin: take the default
        print()
        return default
    if not raw:
        return default
    return cast(raw)


def config_command(args) -> int:
    """Interactive flow (reference cluster.py questionnaire, TPU-sized:
    no GPU-vendor questions, sharding degrees instead of plugin choices).
    Choice questions run through the arrow-key BulletMenu (reference
    commands/menu/) on a TTY, numbered prompts otherwise."""
    from .menu import choose

    cfg = ClusterConfig()
    cfg.compute_environment = choose(
        "Compute environment", ["LOCAL_MACHINE", "TPU_POD"], "LOCAL_MACHINE"
    )
    if cfg.compute_environment == "TPU_POD":
        cfg.tpu_name = _ask("TPU pod name", "") or None
        cfg.tpu_zone = _ask("TPU zone", "") or None
        cfg.num_processes = _ask("Number of hosts in the pod", 1, int)
    else:
        cfg.num_processes = _ask("Number of processes (hosts)", 1, int)
    cfg.mixed_precision = choose("Mixed precision", ["no", "fp16", "bf16"], "bf16")
    cfg.sharding_strategy = choose(
        "Sharding strategy",
        ["AUTO", "DDP", "FSDP", "HYBRID", "GRAD_OP", "NONE"],
        "AUTO",
    )
    cfg.fsdp = _ask("FSDP (ZeRO) axis degree (-1 = all devices)", 1, int)
    cfg.tensor_parallel = _ask("Tensor-parallel degree", 1, int)
    cfg.sequence_parallel = _ask("Sequence-parallel (ring attention) degree", 1, int)
    cfg.data_parallel = _ask("Data-parallel degree (-1 = remaining devices)", -1, int)

    path = args.config_file or default_config_file()
    cfg.to_yaml_file(path)
    print(f"accelerate-tpu configuration saved at {path}")
    return 0


def update_command(args) -> int:
    """reference commands/config/update.py: round-trip the yaml through the
    current ClusterConfig so version migrations add new fields with their
    defaults and unknown/stale keys are dropped."""
    from .config_args import load_config_from_file

    path = args.config_file or default_config_file()
    if not os.path.isfile(path):
        print(f"No config file found at {path}; run `accelerate-tpu config` first")
        return 1
    cfg = load_config_from_file(path)
    cfg.to_yaml_file(path)
    print(f"accelerate-tpu configuration updated in place at {path}")
    return 0


def default_command(args) -> int:
    cfg = ClusterConfig(mixed_precision=args.mixed_precision)
    path = args.config_file or default_config_file()
    if os.path.isfile(path):
        print(f"Config file already exists at {path}, skipping")
        return 0
    cfg.to_yaml_file(path)
    print(f"accelerate-tpu default config saved at {path}")
    return 0
