"""`accelerate-tpu estimate` — memory needed to load/train a model
(parity: reference commands/estimate.py:309 — meta-load + dtype table incl.
training with Adam x4; TPU version adds per-chip fit given a mesh size).

Sources: a built-in model preset (decoder:small_1b etc.), ANY local
safetensors checkpoint — single file, sharded index, or per-rank
distributed — read header-only (shapes/dtypes, zero tensor bytes, the
meta-load analog of reference estimate.py:63), or an explicit --params
count. Zero-egress: no Hub downloads."""

from __future__ import annotations

import glob
import json
import os

DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5}


def register(subparsers):
    parser = subparsers.add_parser("estimate", help="Estimate model memory usage")
    parser.add_argument("model", help="preset (decoder:tiny|decoder:small_1b|decoder:llama_7b|encoder:bert_base), checkpoint path, or param count like 7B")
    parser.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16"], choices=list(DTYPE_BYTES))
    parser.add_argument("--num_chips", type=int, default=1, help="Mesh size to report per-chip shares")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.set_defaults(func=estimate_command)
    return parser


def _inspect_checkpoint(path: str):
    """Header-only inspection of any safetensors checkpoint: (param count,
    {stored dtype: bytes}, largest top-level group bytes). No tensor data is
    read — a 70B checkpoint inspects in milliseconds."""
    import numpy as np

    from ..utils.serialization import load_flat_dict, peek_flat_structs

    structs = peek_flat_structs(path)
    if structs is None:  # pickle or exotic format: fall back to a real load
        structs = load_flat_dict(path)
    n = 0
    by_dtype: dict[str, int] = {}
    groups: dict[str, int] = {}
    for key, leaf in structs.items():
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        nbytes = int(size * np.dtype(leaf.dtype).itemsize)
        n += size
        name = np.dtype(leaf.dtype).name
        by_dtype[name] = by_dtype.get(name, 0) + nbytes
        top = key.split("/")[0].split(".")[0]
        groups[top] = groups.get(top, 0) + nbytes
    largest = max(groups.values()) if groups else 0
    return n, by_dtype, largest


def _num_params(model: str):
    """Returns (param count, display name, largest-group bytes | None,
    stored-dtype byte map | None)."""
    if ":" in model and not os.path.exists(model):
        family, preset = model.split(":", 1)
        if family == "decoder":
            from ..models import DecoderConfig

            cfg = getattr(DecoderConfig, preset)() if hasattr(DecoderConfig, preset) else None
            if cfg is None:
                raise SystemExit(f"unknown decoder preset {preset!r}")
            return cfg.num_params, model, None, None
        if family == "encoder":
            import jax
            import jax.numpy as jnp
            import numpy as np

            from ..models import EncoderClassifier, EncoderConfig

            cfg = getattr(EncoderConfig, preset)() if hasattr(EncoderConfig, preset) else None
            if cfg is None:
                raise SystemExit(f"unknown encoder preset {preset!r}")
            abstract = jax.eval_shape(
                lambda: EncoderClassifier(cfg).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
            )
            n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(abstract))
            return n, model, None, None
        raise SystemExit(f"unknown model family {family!r}")
    exists = (
        os.path.exists(model)
        or os.path.exists(model + ".index.json")
        or glob.glob(model + ".rank*.manifest.json")
    )
    if exists:
        n, by_dtype, largest = _inspect_checkpoint(model)
        return n, model, largest, by_dtype
    suffixes = {"K": 1e3, "M": 1e6, "B": 1e9, "T": 1e12}
    s = model.upper().rstrip()
    if s and s[-1] in suffixes:
        return int(float(s[:-1]) * suffixes[s[-1]]), model, None, None
    raise SystemExit(f"cannot interpret model spec {model!r}")


def _fmt(n_bytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n_bytes < 1024 or unit == "TB":
            return f"{n_bytes:.2f} {unit}"
        n_bytes /= 1024
    return f"{n_bytes:.2f} TB"


def estimate_command(args) -> int:
    n, name, largest, by_dtype = _num_params(args.model)
    rows = []
    for dtype in args.dtypes:
        weights = n * DTYPE_BYTES[dtype]
        # training: params + grads (same dtype) + Adam m/v in fp32 + fp32 master
        train = weights + n * DTYPE_BYTES[dtype] + n * 4 * 2 + (n * 4 if dtype != "float32" else 0)
        row = {
            "dtype": dtype,
            "params": n,
            "inference_total": weights,
            "training_total_adam": train,
            "inference_per_chip": weights / args.num_chips,
            "training_per_chip_fsdp": train / args.num_chips,
        }
        if largest is not None:
            # peak-host invariant: the biggest module group that must be
            # resident while streaming (reference README.md:43-45)
            row["largest_group"] = largest
        rows.append(row)
    if args.as_json:
        out = {"model": name, "rows": rows}
        if by_dtype is not None:
            out["checkpoint_dtypes"] = by_dtype
            out["largest_group_bytes"] = largest
        print(json.dumps(out))
        return 0
    print(f"Memory estimate for {name} ({n/1e6:,.0f}M params, mesh of {args.num_chips} chip(s))")
    if by_dtype is not None:
        stored = ", ".join(f"{k}: {_fmt(v)}" for k, v in sorted(by_dtype.items()))
        print(f"checkpoint stores: {stored}; largest module group {_fmt(largest)}")
    header = f"{'dtype':>9} | {'inference':>12} | {'train (Adam)':>13} | {'infer/chip':>12} | {'train/chip':>12}"
    print(header)
    print("-" * len(header))
    for r in rows:
        print(
            f"{r['dtype']:>9} | {_fmt(r['inference_total']):>12} | {_fmt(r['training_total_adam']):>13} "
            f"| {_fmt(r['inference_per_chip']):>12} | {_fmt(r['training_per_chip_fsdp']):>12}"
        )
    return 0
