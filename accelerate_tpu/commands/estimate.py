"""`accelerate-tpu estimate` — memory needed to load/train a model
(parity: reference commands/estimate.py:309 — meta-load + dtype table incl.
training with Adam x4; TPU version adds per-chip fit given a mesh size).

Sources: a built-in model preset (decoder:small_1b etc.), a local
checkpoint (safetensors/sharded), or explicit --params count. Zero-egress:
no Hub downloads."""

from __future__ import annotations

import json
import os

DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5}


def register(subparsers):
    parser = subparsers.add_parser("estimate", help="Estimate model memory usage")
    parser.add_argument("model", help="preset (decoder:tiny|decoder:small_1b|decoder:llama_7b|encoder:bert_base), checkpoint path, or param count like 7B")
    parser.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16"], choices=list(DTYPE_BYTES))
    parser.add_argument("--num_chips", type=int, default=1, help="Mesh size to report per-chip shares")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.set_defaults(func=estimate_command)
    return parser


def _num_params(model: str) -> tuple[int, str]:
    if ":" in model and not os.path.exists(model):
        family, preset = model.split(":", 1)
        if family == "decoder":
            from ..models import DecoderConfig

            cfg = getattr(DecoderConfig, preset)() if hasattr(DecoderConfig, preset) else None
            if cfg is None:
                raise SystemExit(f"unknown decoder preset {preset!r}")
            return cfg.num_params, model
        if family == "encoder":
            from ..models import EncoderClassifier, EncoderConfig
            import jax
            import jax.numpy as jnp
            import numpy as np

            cfg = getattr(EncoderConfig, preset)() if hasattr(EncoderConfig, preset) else None
            if cfg is None:
                raise SystemExit(f"unknown encoder preset {preset!r}")
            abstract = jax.eval_shape(
                lambda: EncoderClassifier(cfg).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
            )
            n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(abstract))
            return n, model
        raise SystemExit(f"unknown model family {family!r}")
    if os.path.exists(model):
        from ..utils.serialization import load_flat_dict
        import numpy as np

        flat = load_flat_dict(model)
        return sum(int(np.prod(v.shape)) for v in flat.values()), model
    # "7B" / "350M" style
    suffixes = {"K": 1e3, "M": 1e6, "B": 1e9, "T": 1e12}
    s = model.upper().rstrip()
    if s and s[-1] in suffixes:
        return int(float(s[:-1]) * suffixes[s[-1]]), model
    raise SystemExit(f"cannot interpret model spec {model!r}")


def _fmt(n_bytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n_bytes < 1024 or unit == "TB":
            return f"{n_bytes:.2f} {unit}"
        n_bytes /= 1024
    return f"{n_bytes:.2f} TB"


def estimate_command(args) -> int:
    n, name = _num_params(args.model)
    rows = []
    for dtype in args.dtypes:
        weights = n * DTYPE_BYTES[dtype]
        # training: params + grads (same dtype) + Adam m/v in fp32 + fp32 master
        train = weights + n * DTYPE_BYTES[dtype] + n * 4 * 2 + (n * 4 if dtype != "float32" else 0)
        rows.append(
            {
                "dtype": dtype,
                "params": n,
                "inference_total": weights,
                "training_total_adam": train,
                "inference_per_chip": weights / args.num_chips,
                "training_per_chip_fsdp": train / args.num_chips,
            }
        )
    if args.as_json:
        print(json.dumps({"model": name, "rows": rows}))
        return 0
    print(f"Memory estimate for {name} ({n/1e6:,.0f}M params, mesh of {args.num_chips} chip(s))")
    header = f"{'dtype':>9} | {'inference':>12} | {'train (Adam)':>13} | {'infer/chip':>12} | {'train/chip':>12}"
    print(header)
    print("-" * len(header))
    for r in rows:
        print(
            f"{r['dtype']:>9} | {_fmt(r['inference_total']):>12} | {_fmt(r['training_total_adam']):>13} "
            f"| {_fmt(r['inference_per_chip']):>12} | {_fmt(r['training_per_chip_fsdp']):>12}"
        )
    return 0
