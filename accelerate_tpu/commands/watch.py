"""`accelerate-tpu watch` — a live terminal dashboard over the ops plane.

`report` explains a finished run; `watch` shows a running one: sparkline
history for the key serving/training gauges, currently-firing alerts,
and the per-tenant usage table — refreshed in place, pure stdlib, no jax
(locked by tests/test_imports.py), so it runs from any shell that can
reach the scrape endpoint or the artifact dir.

Two data sources:

    accelerate-tpu watch http://localhost:9109/metrics   # live scrape
    accelerate-tpu watch runs/exp/telemetry              # timeline files

- **URL mode** polls the Prometheus exposition the session already
  serves (``TelemetryConfig(exporter_port=...)``), accumulating history
  client-side — no server-side state beyond the existing endpoint.
- **Dir mode** tails ``timeline-host*.jsonl`` / ``alerts-host*.jsonl`` /
  ``usage-host*.json``, so it also works *offline* after the run (or on
  a log-only machine), replaying whatever history the files hold.

``--once`` renders a single frame and exits (scripting / tests);
``--series`` overrides which gauges get sparklines.

**Fleet mode** (``--fleet``, target = comma-separated replica scrape
URLs and/or telemetry dirs) runs a :class:`~..telemetry.fleet.FleetCollector`
client-side and renders the ranked replica health/placement table (the
same ``placement_view()`` the router consumes), fleet-aggregate
sparklines (counters summed, latency quantiles exactly merged from the
native histograms), firing fleet alerts, and recent health transitions.
When a router's ``/metrics`` is among the targets, a **router section**
adds inflight/queue-depth sparklines, the shed-reason breakdown, and
the synthetic-canary pass/fail status line.
"""

from __future__ import annotations

import json
import os
import sys
import time

SPARK_CHARS = " ▁▂▃▄▅▆▇█"
DEFAULT_SERIES = (
    "serving/tokens_per_s",
    "serving/itl_recent_p99_ms",
    "serving/ttft_p99_ms",
    "serving/queue_depth",
    "serving/slot_occupancy",
    "serving/pages_in_use",
    "goodput/goodput_frac",
    "sys/tokens_per_s",
    "sys/mfu_pct",
)
USAGE_COLUMNS = (
    "prefill_tokens", "decode_tokens", "page_seconds", "compute_ms",
    "finished", "shed", "preempted",
)


def sparkline(values, width: int = 32) -> str:
    """Scale a series onto ``width`` block characters (flat series render
    mid-height so a constant gauge is visibly alive, not empty)."""
    vals = [v for v in values if isinstance(v, (int, float))]
    if not vals:
        return " " * width
    if len(vals) > width:
        stride = len(vals) / width
        vals = [vals[int(i * stride)] for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in vals:
        if span <= 0:
            out.append(SPARK_CHARS[4])
        else:
            idx = 1 + int((v - lo) / span * (len(SPARK_CHARS) - 2))
            out.append(SPARK_CHARS[min(idx, len(SPARK_CHARS) - 1)])
    return "".join(out).ljust(width)


def _fmt_num(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != v:
            return "nan"
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        return f"{v:.3g}"
    return str(v)


# -- URL mode: parse the Prometheus exposition back into flat gauges -------


def parse_prometheus(text: str) -> tuple:
    """→ ``(gauges, alerts)``: ``att_*`` gauge lines as a flat dict (the
    ``att_`` prefix stripped), and ``att_alert_firing{rule=...}`` series
    as ``{rule: 0/1}``. Delegates to THE hardened exposition parser in
    ``telemetry.fleet`` (NaN/±Inf values, escaped labels, torn lines) —
    one parser for ``watch`` and the fleet collector, so they can never
    drift."""
    from ..telemetry.fleet import parse_exposition

    snap = parse_exposition(text)
    return snap.gauges, snap.alerts


def fetch_metrics(url: str, timeout_s: float = 5.0) -> tuple:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return parse_prometheus(resp.read().decode("utf-8", "replace"))


def _match_series(available, wanted) -> list:
    """Map wanted timeline keys onto exposition-flattened names, using
    THE exporter's own sanitizer so the two can never drift."""
    from ..telemetry.exporter import PREFIX, _metric_name

    out = []
    for key in wanted:
        flat = _metric_name(key)[len(PREFIX):]
        if key in available:
            out.append(key)
        elif flat in available:
            out.append(flat)
    return out


def _usage_rows_from_gauges(gauges: dict) -> dict:
    """Reassemble the per-tenant table from flattened ``usage_*`` gauge
    names (suffix-matched: tenant ids may themselves contain ``_``)."""
    rows: dict = {}
    for name, v in gauges.items():
        if not name.startswith("usage_"):
            continue
        body = name[len("usage_"):]
        for f in USAGE_COLUMNS + ("submitted", "cancelled", "prefix_hit_tokens"):
            suffix = "_" + f
            if body.endswith(suffix):
                tenant = body[: -len(suffix)]
                if tenant and tenant != "tenants":
                    rows.setdefault(tenant, {})[f] = v
                break
    return rows


# -- dir mode ---------------------------------------------------------------


def load_dir_frame(target: str, span_s: float = 600.0) -> dict:
    """One frame's data from the artifact dir: per-key history out of the
    timeline files, alert states out of the event log, the tenant table
    out of the usage snapshots."""
    from ..telemetry.alerts import load_alerts
    from ..telemetry.timeline import load_timeline
    from ..telemetry.usage import load_usage

    tl = load_timeline(target)
    now = tl.last_t
    history = {}
    gauges = {}
    if now is not None:
        for key in tl.keys():
            pts = tl.series(key, span_s, now=now)
            if pts:
                history[key] = [v for _, v in pts]
                gauges[key] = history[key][-1]
    alerts_data = load_alerts(target)
    alerts = {
        name: int(r.get("state") == "firing")
        for name, r in (alerts_data.get("rules") or {}).items()
    }
    # the most recent firing edge's exemplar request ids per rule — the
    # culprits `accelerate-tpu incident` / `trace --request-id` expand
    exemplars: dict = {}
    for evt in alerts_data.get("events") or []:
        if evt.get("state") == "firing" and evt.get("exemplars"):
            exemplars[evt["rule"]] = evt["exemplars"]
    usage = load_usage(target)
    return {
        "gauges": gauges,
        "history": history,
        "alerts": alerts,
        "alert_exemplars": exemplars,
        "tenants": usage.get("tenants") or {},
        "samples": tl.sample_count,
        "last_t": now,
    }


# -- rendering --------------------------------------------------------------


def render_frame(frame: dict, series_keys, width: int = 32) -> str:
    gauges = frame.get("gauges") or {}
    history = frame.get("history") or {}
    alerts = frame.get("alerts") or {}
    tenants = frame.get("tenants") or {}
    lines = []
    stamp = time.strftime("%H:%M:%S")
    src = frame.get("source", "")
    lines.append(f"accelerate-tpu watch · {src} · {stamp}"
                 + (f" · {frame['samples']} samples" if frame.get("samples") else ""))
    lines.append("")
    keys = _match_series(set(gauges) | set(history), series_keys)
    if not keys:
        lines.append("  (no known series yet — is the session sampling?)")
    for key in keys:
        hist = history.get(key) or []
        cur = gauges.get(key, hist[-1] if hist else None)
        lo = min(hist) if hist else None
        hi = max(hist) if hist else None
        lines.append(
            f"  {key:<32} {_fmt_num(cur):>10}  {sparkline(hist, width)}"
            f"  [{_fmt_num(lo)} .. {_fmt_num(hi)}]"
        )
    lines.append("")
    if alerts:
        firing = sorted(n for n, v in alerts.items() if v)
        quiet = sorted(n for n, v in alerts.items() if not v)
        if firing:
            lines.append("  ALERTS FIRING: " + ", ".join(firing))
            exemplars = frame.get("alert_exemplars") or {}
            for name in firing:
                ids = exemplars.get(name)
                if ids:
                    lines.append(
                        f"    {name} culprits: "
                        + ", ".join(str(r) for r in ids[:4])
                        + "  (trace summary --request-id <id>)"
                    )
        lines.append("  alerts ok: " + (", ".join(quiet) if quiet else "(none)"))
    else:
        lines.append("  alerts: (none configured / no events yet)")
    if tenants:
        from .report import render_table

        lines.append("")
        table = [("tenant",) + USAGE_COLUMNS]
        order = sorted(
            tenants, key=lambda t: -(tenants[t].get("decode_tokens") or 0)
        )
        for name in order[:12]:
            row = tenants[name]
            table.append((name,) + tuple(_fmt_num(row.get(c))
                                         for c in USAGE_COLUMNS))
        lines.extend(render_table(table))
    return "\n".join(lines)


def _build_frame(target: str, history: dict, span_s: float) -> dict:
    if target.startswith(("http://", "https://")):
        gauges, alerts = fetch_metrics(target)
        for key, v in gauges.items():
            history.setdefault(key, []).append(v)
            if len(history[key]) > 240:
                del history[key][: len(history[key]) - 240]
        return {
            "source": target,
            "gauges": gauges,
            "history": history,
            "alerts": alerts,
            "tenants": _usage_rows_from_gauges(gauges),
        }
    frame = load_dir_frame(target, span_s=span_s)
    frame["source"] = target
    return frame


# -- fleet mode -------------------------------------------------------------

FLEET_SERIES = (
    "serving/tokens_per_s",
    "serving/capacity_tokens_per_s",
    "serving/headroom_frac",
    "serving/itl_p99_ms",
    "serving/queue_depth",
    "serving/pages_in_use",
    "fleet/replicas_placeable",
    "fleet/replicas_down",
)
FLEET_COLUMNS = ("replica", "state", "load", "queue", "free_slots",
                 "tok/s", "itl_p99", "age_s")


def render_fleet_frame(collector, series_keys, width: int = 32,
                       span_s: float = 600.0) -> str:
    """One ``watch --fleet`` frame: the ranked replica table (state +
    load score — the same placement_view() the router consumes), the
    fleet-aggregate sparklines, firing fleet alerts, and the most recent
    health transitions."""
    from .report import render_table

    tl = collector.timeline
    now = tl.last_t
    lines = [
        f"accelerate-tpu watch --fleet · {len(collector.replicas)} replicas"
        f" · {time.strftime('%H:%M:%S')} · poll {collector.polls}"
    ]
    lines.append("")
    table = [FLEET_COLUMNS]
    for row in collector.placement_view(include_unplaceable=True):
        score = row.get("load_score")
        table.append((
            row["replica"],
            row["state"] + ("" if row["placeable"] else " ✗"),
            _fmt_num(round(score, 3) if isinstance(score, float) else score),
            _fmt_num(row.get("queue_depth")),
            _fmt_num(row.get("free_slots")),
            _fmt_num(row.get("tokens_per_s")),
            _fmt_num(row.get("itl_recent_p99_ms")),
            _fmt_num(row.get("last_ok_age_s")),
        ))
    lines.extend(render_table(table))
    lines.append("")
    if now is not None:
        for key in series_keys:
            pts = tl.series(key, span_s, now=now)
            if not pts:
                continue
            hist = [v for _, v in pts]
            lines.append(
                f"  {key:<32} {_fmt_num(hist[-1]):>10}  "
                f"{sparkline(hist, width)}  "
                f"[{_fmt_num(min(hist))} .. {_fmt_num(max(hist))}]"
            )
    lines.extend(_capacity_section(collector))
    lines.extend(_router_section(collector, width=width, span_s=span_s))
    lines.extend(_cache_economics_section(collector))
    states = collector.alerts.states_snapshot()
    firing = sorted(n for n, st in states.items() if st["state"] == "firing")
    lines.append("")
    if firing:
        lines.append("  ALERTS FIRING: " + ", ".join(firing))
        for name in firing:
            ids = states[name].get("exemplars")
            if ids:
                lines.append(
                    f"    {name} culprits: "
                    + ", ".join(str(r) for r in ids[:4])
                    + "  (trace summary --request-id <id>)"
                )
    if states:
        quiet = sorted(n for n in states if n not in firing)
        lines.append("  alerts ok: " + (", ".join(quiet) if quiet else "(none)"))
    else:
        lines.append("  alerts: (none configured)")
    events = collector.events[-5:]
    if events:
        lines.append("")
        lines.append("  recent health transitions:")
        for evt in events:
            lines.append(
                f"    {evt['replica']}: {evt['from']} -> {evt['to']} "
                f"({evt['reason']})"
            )
    return "\n".join(lines)


ROUTER_SERIES = ("router/inflight", "serving/queue_depth")


def _capacity_section(collector) -> list:
    """The offered-vs-capacity block of a fleet frame — present once any
    replica exports the capacity gauges (``telemetry/capacity.py``):
    fleet capacity (sums over live replicas), offered rate against it,
    and — when an autoscaler publishes through a scraped router — the
    daemon's own counters and last reaction time."""
    from ..telemetry.capacity import fleet_capacity

    gauges = collector.fleet_gauges()
    row = fleet_capacity(gauges)
    lines = []
    if row is not None:
        lines.extend(["", (
            "  capacity: "
            f"offered {_fmt_num(row['offered_tokens_per_s'])} / "
            f"{_fmt_num(row['capacity_tokens_per_s'])} tok/s"
            f" · utilization {_fmt_num(row['utilization_frac'])}"
            f" · headroom {_fmt_num(row['headroom_frac'])}"
        )])
    evals = gauges.get("autoscale/evals")
    if evals:
        reaction = gauges.get("autoscale/last_reaction_s")
        if not lines:
            lines.append("")
        lines.append(
            "  autoscale: "
            f"{_fmt_num(gauges.get('autoscale/scale_outs'))} out / "
            f"{_fmt_num(gauges.get('autoscale/scale_ins'))} in over "
            f"{_fmt_num(evals)} evals"
            f" · owned {_fmt_num(gauges.get('autoscale/replicas_owned'))}"
            + (f" · last reaction {_fmt_num(reaction)}s"
               if reaction is not None else "")
        )
    return lines


def _cache_economics_section(collector) -> list:
    """The prefix-cache economics block of a fleet frame — present only
    when replicas export the ghost-cache gauges (``serving/ghost_*``,
    serving/pages.py): actual hit ratio next to what 2x/4x/10x the
    capacity WOULD buy, plus the reuse-after-evict distances that say
    how far away the wasted re-prefills are. The gap between actual and
    ghost ratios is the measured headroom a KV tier would capture."""
    gauges = collector.fleet_gauges()
    ghosts = {k: v for k, v in gauges.items()
              if k.startswith("serving/ghost_")}
    if not ghosts:
        return []
    actual = gauges.get("serving/prefix_hit_ratio")
    would = " ".join(
        f"{m}x={_fmt_num(ghosts.get(f'serving/ghost_hit_ratio_{m}x'))}"
        for m in (2, 4, 10)
        if ghosts.get(f"serving/ghost_hit_ratio_{m}x") is not None
    )
    lines = ["", (
        "  cache economics: "
        f"prefix hit ratio {_fmt_num(actual)}"
        + (f" · at capacity {would}" if would else "")
        + f" · reuse-after-evict {_fmt_num(ghosts.get('serving/ghost_reuses'))}"
    )]
    p50 = ghosts.get("serving/ghost_reuse_distance_p50")
    p99 = ghosts.get("serving/ghost_reuse_distance_p99")
    if p50 is not None or p99 is not None:
        lines.append(
            f"  reuse distance p50/p99: {_fmt_num(p50)}/{_fmt_num(p99)} "
            "lookups (small = a modest capacity bump recovers them)"
        )
    # per-tier hit breakdown (hierarchical KV tiering, serving/tiers.py):
    # where hits actually land once demote-on-evict is on — the ghost
    # ratios above now measure headroom BEYOND the total tier capacity
    tiers = " ".join(
        f"{t}={_fmt_num(gauges.get(f'serving/kv_tier_hit_ratio_{t}'))}"
        for t in ("hbm", "host", "disk", "peer")
        if gauges.get(f"serving/kv_tier_hit_ratio_{t}") is not None
    )
    if tiers:
        restores = gauges.get("serving/kv_restores")
        aborted = gauges.get("serving/kv_restores_aborted")
        lines.append(
            f"  tier hits: {tiers}"
            + (f" · restores {_fmt_num(restores)}" if restores is not None
               else "")
            + (f" (aborted {_fmt_num(aborted)})" if aborted else "")
        )
    return lines


def _router_section(collector, width: int = 32, span_s: float = 600.0) -> list:
    """The router block of a fleet frame — present only when the fleet
    actually exports ``router/*`` gauges (i.e. a router's ``/metrics``
    is among the scrape targets): inflight/queue-depth sparklines, the
    shed-reason breakdown, and the canary status line."""
    gauges = collector.fleet_gauges()
    router_keys = {k: v for k, v in gauges.items() if k.startswith("router/")}
    if not router_keys:
        return []
    tl = collector.timeline
    now = tl.last_t
    lines = ["", (
        "  router: "
        f"inflight {_fmt_num(gauges.get('router/inflight'))}"
        f" · submitted {_fmt_num(gauges.get('router/requests_submitted'))}"
        f" · completed {_fmt_num(gauges.get('router/requests_completed'))}"
        f" · requeues {_fmt_num(gauges.get('router/requeues'))}"
        + (f" · ttft p99 {_fmt_num(gauges.get('router/ttft_p99_ms'))}ms"
           if gauges.get("router/ttft_p99_ms") is not None else "")
    )]
    if now is not None:
        for key in ROUTER_SERIES:
            pts = tl.series(key, span_s, now=now)
            if not pts:
                continue
            hist = [v for _, v in pts]
            lines.append(
                f"  {key:<32} {_fmt_num(hist[-1]):>10}  "
                f"{sparkline(hist, width)}  "
                f"[{_fmt_num(min(hist))} .. {_fmt_num(max(hist))}]"
            )
    # shed-reason breakdown (both key spellings: raw rollup router/shed/x
    # and exposition-unflattened router/shed_x)
    sheds = {}
    for key, v in router_keys.items():
        if key.startswith("router/shed") and key != "router/shed":
            reason = key[len("router/shed"):].lstrip("/_")
            if reason and v:
                sheds[reason] = v
    if sheds:
        lines.append("  shed reasons: " + ", ".join(
            f"{r}={_fmt_num(v)}" for r, v in
            sorted(sheds.items(), key=lambda kv: -kv[1])
        ))
    sent = gauges.get("canary/probes_sent")
    if sent:
        ratio = gauges.get("canary/pass_ratio")
        ok = ratio is not None and ratio >= 1.0
        age = None
        last_pass = gauges.get("canary/last_pass_unix_s")
        if isinstance(last_pass, (int, float)) and last_pass > 0:
            age = max(0.0, time.time() - last_pass)
        lines.append(
            f"  canary: {'OK' if ok else 'FAILING'}"
            f" · pass ratio {_fmt_num(ratio)}"
            f" · {_fmt_num(sent)} probes"
            f" · {_fmt_num(gauges.get('canary/probes_failed'))} failed"
            + (f" · last pass {age:.0f}s ago" if age is not None else "")
        )
    return lines


def watch_fleet_command(args) -> int:
    from ..telemetry.fleet import FleetCollector

    targets = [t.strip() for t in args.target.split(",") if t.strip()]
    series = (
        [s.strip() for s in args.series.split(",") if s.strip()]
        if args.series else list(FLEET_SERIES)
    )
    try:
        collector = FleetCollector(
            targets,
            poll_interval_s=args.interval,
            stale_after_s=args.stale_after,
            dead_after_s=args.dead_after,
        )
    except ValueError as e:
        print(f"watch --fleet: {e}", file=sys.stderr)
        return 1
    try:
        while True:
            collector.poll_once()
            text = render_fleet_frame(collector, series, width=args.width,
                                      span_s=args.span)
            if args.once:
                print(text)
                return 0
            sys.stdout.write("\x1b[H\x1b[2J" + text + "\n")
            sys.stdout.flush()
            try:
                time.sleep(args.interval)
            except KeyboardInterrupt:
                return 0
    finally:
        collector.close()


def watch_command(args) -> int:
    if getattr(args, "fleet", False):
        return watch_fleet_command(args)
    history: dict = {}
    series = (
        [s.strip() for s in args.series.split(",") if s.strip()]
        if args.series else list(DEFAULT_SERIES)
    )
    is_url = args.target.startswith(("http://", "https://"))
    if not is_url and not os.path.isdir(args.target):
        print(f"watch: {args.target} is neither a URL nor a directory",
              file=sys.stderr)
        return 1
    while True:
        try:
            frame = _build_frame(args.target, history, args.span)
        except Exception as e:
            print(f"watch: cannot read {args.target}: {e}", file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        text = render_frame(frame, series, width=args.width)
        if args.once:
            print(text)
            return 0
        # ANSI home+clear keeps the frame in place without flicker
        sys.stdout.write("\x1b[H\x1b[2J" + text + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def register(subparsers):
    parser = subparsers.add_parser(
        "watch",
        help="Live terminal dashboard: gauge sparklines, firing alerts, "
             "per-tenant usage (scrape endpoint or telemetry dir)",
    )
    parser.add_argument(
        "target",
        help="scrape URL (http://host:port/metrics) or telemetry dir "
             "(timeline-host*.jsonl / alerts-host*.jsonl / usage-host*.json); "
             "with --fleet, a comma-separated list of replica URLs/dirs",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="fleet mode: poll N replica scrape endpoints (comma-separated "
             "target), render the ranked replica health/placement table + "
             "fleet-aggregate sparklines (telemetry/fleet.py)",
    )
    parser.add_argument("--stale-after", type=float, default=10.0,
                        help="fleet mode: sample age marking a replica "
                             "degraded (default 10s)")
    parser.add_argument("--dead-after", type=float, default=15.0,
                        help="fleet mode: unreachable time marking a replica "
                             "dead (default 15s)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh cadence in seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit (scripting)")
    parser.add_argument("--series", default=None,
                        help="comma-separated gauge keys to sparkline "
                             "(default: the serving/goodput headliners)")
    parser.add_argument("--span", type=float, default=600.0,
                        help="dir mode: history window seconds (default 600)")
    parser.add_argument("--width", type=int, default=32,
                        help="sparkline width in characters")
    parser.set_defaults(func=watch_command)
    return parser
