"""`accelerate-tpu launch` — start a training script on TPU hosts.

Parity target: /root/reference/src/accelerate/commands/launch.py (1,184 LoC).
The torch version multiplexes over torchrun/deepspeed/sagemaker/xmp.spawn;
on TPU the topology is simpler — ONE process per host drives all local
chips — so the dispatch collapses to three launchers:

  simple_launcher      single host: exec the script with env set
                       (reference simple_launcher:762)
  multi_process_launcher
                       N processes on THIS machine with the
                       COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID env
                       contract; used for multi-host-style testing on
                       localhost (the reference's gloo-on-localhost test
                       strategy, SURVEY §4) and by pod fan-out re-entry
  tpu_pod_launcher     gcloud ssh to every TPU-VM worker re-invoking this
                       CLI (reference tpu_pod_launcher:893 = xla_dist)

Precedence: CLI flag > config yaml > default (reference
_validate_launch_command:972 merge semantics).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Optional

from ..utils.environment import env_var
from .config_args import ClusterConfig, load_config_from_file


def register(subparsers):
    parser = subparsers.add_parser("launch", help="Launch a script on this host / a TPU pod")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--num_processes", type=int, default=None, help="Number of host processes")
    parser.add_argument("--num_machines", type=int, default=None, help="Alias of --num_processes (reference parity)")
    parser.add_argument("--mixed_precision", choices=["no", "fp16", "bf16"], default=None)
    parser.add_argument("--cpu", action="store_true", help="Force CPU (with gloo collectives when multi-process)")
    parser.add_argument("--main_process_ip", default=None)
    parser.add_argument("--main_process_port", type=int, default=None)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=None)
    # sharding degrees (the FSDP/DeepSpeed/Megatron arg-group analog)
    for axis in ("data_parallel", "fsdp", "tensor_parallel", "sequence_parallel",
                 "expert_parallel", "pipeline_parallel", "replica"):
        parser.add_argument(f"--{axis}", type=int, default=None)
    parser.add_argument("--sharding_strategy", default=None)
    parser.add_argument("--grad_compression_dtype", default=None,
                        choices=["bfloat16", "float16", "int8", "bf16", "fp16", "none"],
                        help="Compress the cross-slice (DCN) gradient all-reduce; 'none' disables")
    # pod fan-out
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument("--tpu_project", default=None)
    parser.add_argument("--tpu_use_sudo", action="store_true")
    parser.add_argument("--downcast_bf16", action="store_true")
    parser.add_argument("-m", "--module", action="store_true", help="Run script as a python module")
    parser.add_argument("--no_python", action="store_true", help="Exec script directly (not via python)")
    parser.add_argument("--quiet", "-q", action="store_true")
    parser.add_argument("--debug", action="store_true")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="Relaunch the whole world up to N times after a worker failure (elastic parity)")
    parser.add_argument("--monitor_interval", type=float, default=0.1,
                        help="Seconds between worker health polls")
    parser.add_argument("training_script", help="Script (or module) to launch")
    parser.add_argument("training_script_args", nargs=argparse_remainder(), help="Script args")
    parser.set_defaults(func=launch_command)
    return parser


def argparse_remainder():
    import argparse

    return argparse.REMAINDER


def _merge(args, config: ClusterConfig) -> ClusterConfig:
    """CLI overrides config file (reference _validate_launch_command:972)."""
    merged = ClusterConfig(**config.to_dict())
    if args.num_processes is not None:
        merged.num_processes = args.num_processes
    elif args.num_machines is not None:
        merged.num_processes = args.num_machines
    if args.mixed_precision is not None:
        merged.mixed_precision = args.mixed_precision
    if args.main_process_ip is not None:
        merged.main_process_ip = args.main_process_ip
    if args.main_process_port is not None:
        merged.main_process_port = args.main_process_port
    if args.sharding_strategy is not None:
        merged.sharding_strategy = args.sharding_strategy
    for axis in ("data_parallel", "fsdp", "tensor_parallel", "sequence_parallel",
                 "expert_parallel", "pipeline_parallel", "replica"):
        v = getattr(args, axis)
        if v is not None:
            setattr(merged, axis, v)
    if args.grad_compression_dtype is not None:
        merged.grad_compression_dtype = (
            None if args.grad_compression_dtype == "none" else args.grad_compression_dtype
        )
    for flag in ("tpu_name", "tpu_zone", "tpu_project"):
        v = getattr(args, flag)
        if v is not None:
            setattr(merged, flag, v)
    if args.debug:
        merged.debug = True
    if args.downcast_bf16:
        merged.downcast_bf16 = True
    return merged


def prepare_launch_env(config: ClusterConfig, args=None) -> dict:
    """The ACCELERATE_TPU_* env contract consumed by state.py
    (reference prepare_simple_launcher_cmd_env:91 writes ACCELERATE_*)."""
    env = dict(os.environ)
    env[env_var("MIXED_PRECISION")] = config.mixed_precision
    env[env_var("STRATEGY")] = str(config.sharding_strategy)
    for axis, name in (
        ("data_parallel", "DATA_PARALLEL"),
        ("fsdp", "FSDP"),
        ("tensor_parallel", "TENSOR_PARALLEL"),
        ("sequence_parallel", "SEQUENCE_PARALLEL"),
        ("expert_parallel", "EXPERT_PARALLEL"),
        ("pipeline_parallel", "PIPELINE_PARALLEL"),
        ("replica", "REPLICA"),
    ):
        env[env_var(name)] = str(getattr(config, axis))
    # always stomp (like the axis vars): a stale inherited value must not
    # resurrect compression the current config doesn't ask for
    env[env_var("GRAD_COMPRESSION")] = config.grad_compression_dtype or ""
    if config.debug:
        env[env_var("DEBUG_MODE")] = "1"
    if config.downcast_bf16:
        env[env_var("DOWNCAST_BF16")] = "1"
    if config.compilation_cache_dir:
        env[env_var("COMPILATION_CACHE_DIR")] = config.compilation_cache_dir
    if args is not None and getattr(args, "gradient_accumulation_steps", None):
        env[env_var("GRADIENT_ACCUMULATION_STEPS")] = str(args.gradient_accumulation_steps)
    return env


def _script_cmd(args) -> list:
    if args.no_python:
        cmd = [args.training_script]
    elif args.module:
        cmd = [sys.executable, "-m", args.training_script]
    else:
        cmd = [sys.executable, args.training_script]
    return cmd + list(args.training_script_args)


def simple_launcher(args, config: ClusterConfig) -> int:
    """One process on this host drives all its chips (the normal TPU case)."""
    env = prepare_launch_env(config, args)
    if args.cpu:
        _force_cpu(env)
    process = subprocess.Popen(_script_cmd(args), env=env)
    process.wait()
    return process.returncode


def multi_process_launcher(args, config: ClusterConfig) -> int:
    """Spawn num_processes local processes with the distributed env contract
    (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID). With --cpu this is the
    debug/gloo-on-localhost path; on a pod worker it re-enters per host.

    Monitors the whole world: the first worker to exit non-zero gets the rest
    killed (survivors would otherwise hang in collectives), and with
    ``--max_restarts`` the world is relaunched on a fresh port — the
    torchrun-elastic restart semantic (reference launch.py:774-806)."""
    n = config.num_processes
    ip = config.main_process_ip or "127.0.0.1"
    base_env = prepare_launch_env(config, args)
    max_restarts = getattr(args, "max_restarts", 0) or 0
    interval = getattr(args, "monitor_interval", 0.1) or 0.1
    for attempt in range(max_restarts + 1):
        # fresh port each attempt: the old coordinator socket may linger
        port = config.main_process_port if (config.main_process_port and attempt == 0) else _free_port()
        procs = []
        for rank in range(n):
            env = dict(base_env)
            env[env_var("COORDINATOR_ADDRESS")] = f"{ip}:{port}"
            env[env_var("NUM_PROCESSES")] = str(n)
            env[env_var("PROCESS_ID")] = str(rank)
            env[env_var("LOCAL_PROCESS_ID")] = str(rank)
            env[env_var("RESTART_COUNT")] = str(attempt)
            if args.cpu:
                _force_cpu(env)
            procs.append(subprocess.Popen(_script_cmd(args), env=env))
        from ..launchers import _subprocess_group_kwargs, monitor_group

        code = monitor_group(procs, interval=interval, **_subprocess_group_kwargs())
        if code == 0:
            return 0
        if attempt < max_restarts:
            print(f"[accelerate-tpu launch] worker failed (exit {code}); "
                  f"restart {attempt + 1}/{max_restarts}", file=sys.stderr)
    return code


def tpu_pod_launcher(args, config: ClusterConfig) -> int:
    """gcloud ssh fan-out: run the same launch on every TPU-VM worker
    (reference tpu_pod_launcher:893). jax.distributed auto-discovers the
    pod topology from TPU metadata, so workers need no rank env."""
    script_cmd = " ".join(shlex.quote(c) for c in _script_cmd(args))
    env_exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in prepare_launch_env(config, args).items()
        if k.startswith(env_var(""))
    )
    remote = f"cd {shlex.quote(os.getcwd())} && {env_exports} {script_cmd}"
    if args.tpu_use_sudo:
        remote = "sudo " + remote
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", config.tpu_name,
        f"--zone={config.tpu_zone}",
        "--worker=all",
        f"--command={remote}",
    ]
    if config.tpu_project:
        cmd.append(f"--project={config.tpu_project}")
    process = subprocess.Popen(cmd)
    process.wait()
    return process.returncode


def _force_cpu(env: dict) -> None:
    """Make child processes actually use CPU: besides JAX_PLATFORMS, drop
    platform-plugin triggers that force-register an accelerator at
    interpreter start (e.g. the axon TPU-tunnel sitecustomize)."""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def launch_command(args) -> int:
    config = _merge(args, load_config_from_file(args.config_file))
    if config.tpu_name:
        return tpu_pod_launcher(args, config)
    if config.num_processes and config.num_processes > 1:
        return multi_process_launcher(args, config)
    return simple_launcher(args, config)


def main():  # pragma: no cover - direct entry
    import argparse

    parser = argparse.ArgumentParser("accelerate-tpu launch")
    sub = parser.add_subparsers()
    register(sub)
    args = parser.parse_args(["launch"] + sys.argv[1:])
    sys.exit(args.func(args))
