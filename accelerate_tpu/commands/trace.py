"""`accelerate-tpu trace` — inspect the telemetry dir's serving artifacts.

A multi-host run leaves one Chrome-trace span JSONL and one request-log
JSONL per host in its telemetry dir; this command turns them back into
answers without a notebook:

    accelerate-tpu trace merge runs/exp/telemetry -o merged.json
    accelerate-tpu trace merge runs/exp/telemetry --request-id 42
    accelerate-tpu trace summary runs/exp/telemetry
    accelerate-tpu trace summary runs/exp/telemetry --request-id 42 --json
    accelerate-tpu trace summary runs/exp/telemetry --waterfall

``merge`` folds every host's span stream into ONE Perfetto-loadable
Chrome trace (hosts stay separate rows via their pid; per-host clock
epochs are aligned through the ``epoch_unix_s`` metadata each recorder
writes), optionally filtered to the spans of a single request.
``summary`` renders the request-log JSONL as a latency table — one row
per request plus aggregate TTFT/ITL/queue-wait percentiles from the same
log-bucketed histograms the live session uses — or, with
``--request-id``, the full lifecycle of one request (prefill chunk plan,
ITL series, compile activity). ``summary --waterfall`` joins the
router's own request log (``router-requests*.jsonl``) with the replica
request logs and decomposes each request's client-observed TTFT into
router-queue → placement → retry-backoff → transport → replica-queue →
prefill stages that sum to the total (``telemetry/waterfall.py``;
docs/serving.md "Reading the request waterfall"). Pure stdlib + the
telemetry host modules: no jax import, so it runs anywhere the log
files land.
"""

from __future__ import annotations

import glob
import json
import os
import sys


def _span_files(target: str) -> list:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "trace-host*.jsonl")))
    return [target]


def _request_files(target) -> list:
    """Request-log files for one target or a list of targets (each a
    telemetry dir or one ``requests-host*.jsonl``) — N replicas each own
    a telemetry dir, and stitching needs all of them at once."""
    targets = [target] if isinstance(target, str) else list(target)
    out = []
    for t in targets:
        if os.path.isdir(t):
            out.extend(sorted(glob.glob(os.path.join(t, "requests-host*.jsonl"))))
        else:
            out.append(t)
    return out


def _same_id(a, b) -> bool:
    """Request-id equality across int/str sources (the CLI arg is a
    string; engine-assigned ids are ints, router-supplied ids may be
    either)."""
    return a == b or str(a) == str(b)


def merge_traces(target: str, request_id=None) -> dict:
    """Merge per-host span JSONLs into one ``{"traceEvents": [...]}``.

    Each recorder rebases its ``ts`` clock to its own start; the
    ``process_name`` metadata line carries ``epoch_unix_s``, so hosts are
    shifted onto the earliest host's axis before merging. With
    ``request_id``, only that request's spans (events whose args carry the
    id) plus the metadata rows survive."""
    per_host = []
    for path in _span_files(target):
        events = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
        epoch = None
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                epoch = (e.get("args") or {}).get("epoch_unix_s")
                break
        per_host.append((epoch, events))
    if not per_host:
        return {"traceEvents": []}
    epochs = [ep for ep, _ in per_host if ep is not None]
    base = min(epochs) if epochs else None
    merged = []
    for epoch, events in per_host:
        shift_us = (epoch - base) * 1e6 if (epoch is not None and base is not None) else 0.0
        for e in events:
            if e.get("ph") == "M":
                merged.append(e)
                continue
            if request_id is not None:
                if not _same_id((e.get("args") or {}).get("request_id"),
                                request_id):
                    continue
            if shift_us and "ts" in e:
                e = dict(e, ts=round(e["ts"] + shift_us, 3))
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ph") == "M" and -1) or e.get("ts", 0))
    return {"traceEvents": merged}


def load_requests(target) -> list:
    """Every request record in the dir(s)/file(s), tagged with its source
    host (``target`` may be a list of telemetry dirs — one per replica)."""
    out = []
    for path in _request_files(target):
        name = os.path.basename(path)
        host = name[len("requests-host"):-len(".jsonl")] if name.startswith("requests-host") else "?"
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    rec = json.loads(line)
                    rec.setdefault("host", host)
                    out.append(rec)
    out.sort(key=lambda r: (r.get("submit_unix_s", 0), r.get("request_id", 0)))
    return out


def summarize_requests(records: list) -> dict:
    """Aggregate latency stats over request records — the same
    ``StreamingHistogram`` percentiles the live session reports."""
    from ..telemetry.histograms import StreamingHistogram

    hists = {"queue_wait_ms": StreamingHistogram(), "ttft_ms": StreamingHistogram(),
             "total_ms": StreamingHistogram(), "itl_ms": StreamingHistogram()}
    tokens = 0
    reasons: dict = {}
    outcomes: dict = {}
    preemptions = 0
    prefix_hits = prefix_tokens = prompt_tokens = 0
    spec_proposed = spec_accepted = pages = 0
    for rec in records:
        for key in ("queue_wait_ms", "ttft_ms", "total_ms"):
            v = rec.get(key)
            if isinstance(v, (int, float)):
                hists[key].add(v / 1e3)
        for v in rec.get("itl_ms") or []:
            hists["itl_ms"].add(v / 1e3)
        tokens += rec.get("tokens") or 0
        reason = rec.get("finish_reason", "?")
        reasons[reason] = reasons.get(reason, 0) + 1
        outcome = rec.get("outcome")
        if outcome:
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
        preemptions += rec.get("preemptions") or 0
        hit = rec.get("prefix_hit") or 0
        prefix_hits += 1 if hit else 0
        prefix_tokens += hit
        prompt_tokens += rec.get("prompt_len") or 0
        spec_proposed += rec.get("spec_proposed") or 0
        spec_accepted += rec.get("spec_accepted") or 0
        pages += rec.get("pages_allocated") or 0
    agg = {"requests": len(records), "tokens": tokens, "finish_reasons": reasons}
    if outcomes:
        # the definite-outcome contract: every submitted request landed as
        # finished / shed / cancelled (an "evicted" here means a request
        # was abandoned at close — the thing drain() exists to prevent)
        agg["outcomes"] = outcomes
    if preemptions:
        agg["preemptions"] = preemptions
    if prefix_tokens or spec_proposed or pages:
        # paged-arena attribution: which share of requests (and of prompt
        # tokens) the prefix cache served, and how speculation fared
        agg["prefix_hit_requests"] = prefix_hits
        agg["prefix_hit_ratio"] = round(prefix_hits / len(records), 4) if records else 0.0
        if prompt_tokens:
            agg["prefix_hit_token_frac"] = round(prefix_tokens / prompt_tokens, 4)
        agg["pages_allocated"] = pages
        if spec_proposed:
            agg["spec_accept_rate"] = round(spec_accepted / spec_proposed, 4)
    for key, hist in hists.items():
        snap = hist.snapshot()
        if snap:
            agg[f"{key[:-3]}_p50_ms"] = round(snap["p50_s"] * 1e3, 3)
            agg[f"{key[:-3]}_p95_ms"] = round(snap["p95_s"] * 1e3, 3)
            agg[f"{key[:-3]}_p99_ms"] = round(snap["p99_s"] * 1e3, 3)
    return agg


def stitch_request(records: list) -> dict:
    """Merge one logical request's records — one per replica hop — into
    a hop-by-hop timeline. A router re-queuing a request (replica died,
    preemptive re-placement) submits the SAME external ``request_id`` to
    each replica; each replica's log holds its own hop. Hops order by
    submit time; ``gap_ms`` is the hand-off latency between one hop's
    finish and the next hop's submit (the router's re-queue cost)."""
    hops = sorted(records, key=lambda r: r.get("submit_unix_s", 0))
    out_hops = []
    prev_finish = None
    for i, rec in enumerate(hops):
        hop = {
            "hop": i,
            "replica": rec.get("replica") or rec.get("host", "?"),
            "submit_unix_s": rec.get("submit_unix_s"),
            "queue_wait_ms": rec.get("queue_wait_ms"),
            "ttft_ms": rec.get("ttft_ms"),
            "tokens": rec.get("tokens", 0),
            "total_ms": rec.get("total_ms"),
            "outcome": rec.get("outcome"),
            "finish_reason": rec.get("finish_reason"),
            "preemptions": rec.get("preemptions", 0),
        }
        submit = rec.get("submit_unix_s")
        if prev_finish is not None and submit is not None:
            hop["gap_ms"] = round((submit - prev_finish) * 1e3, 3)
        prev_finish = rec.get("finish_unix_s")
        out_hops.append(hop)
    first = hops[0].get("submit_unix_s")
    last = hops[-1].get("finish_unix_s")
    return {
        "request_id": hops[0].get("request_id"),
        "hops": out_hops,
        "hop_count": len(out_hops),
        "tokens": sum(h["tokens"] or 0 for h in out_hops),
        "end_to_end_ms": (
            round((last - first) * 1e3, 3)
            if first is not None and last is not None else None
        ),
        "outcome": out_hops[-1].get("outcome"),
    }


def _format_stitched(stitched: dict) -> str:
    from .report import render_table  # the one shared table renderer

    rows = [("hop", "replica", "queue_ms", "ttft_ms", "tokens", "total_ms",
             "gap_ms", "outcome", "reason")]
    for h in stitched["hops"]:
        rows.append((
            h["hop"], h["replica"], h.get("queue_wait_ms", ""),
            h.get("ttft_ms", ""), h.get("tokens", ""),
            h.get("total_ms", ""), h.get("gap_ms", ""),
            h.get("outcome", ""), h.get("finish_reason", ""),
        ))
    lines = [f"request {stitched['request_id']}: {stitched['hop_count']} hop(s) "
             f"across replicas, {stitched['tokens']} tokens"
             + (f", end-to-end {stitched['end_to_end_ms']} ms"
                if stitched.get("end_to_end_ms") is not None else "")]
    lines.extend(render_table(rows, indent=""))
    return "\n".join(lines)


def build_waterfall_rows(target, router_records=None) -> list:
    """Join a telemetry dir's router request log with its replica
    request logs and decompose — the shared load half of
    ``summary --waterfall`` and ``report``'s waterfall section."""
    from ..telemetry.waterfall import build_waterfalls, load_router_requests

    if router_records is None:
        router_records = load_router_requests(target)
    if not router_records:
        return []
    replica_recs = load_requests(target) if _request_files(target) else []
    return build_waterfalls(router_records, replica_recs)


def _format_waterfall(rows: list, agg: dict) -> str:
    """The waterfall table: one row per request (stage columns in causal
    order), then the per-stage percentile aggregate — the 'which stage
    ate the p99' answer."""
    from ..telemetry.waterfall import STAGES, stage_table

    from .report import render_table  # the one shared table renderer

    table = [("id", "replica", "hops", "e2e_ttft_ms")
             + tuple(f"{s}_ms" for s in STAGES) + ("top",)]
    for row in rows:
        table.append((
            str(row.get("request_id")), str(row.get("replica")),
            str(1 + (row.get("requeues") or 0)),
            str(row.get("e2e_ttft_ms")),
        ) + tuple(str(row["stages"].get(s, "")) for s in STAGES)
          + (row.get("top_stage", ""),))
    lines = [
        f"{agg.get('requests', 0)} request(s) decomposed "
        f"({agg.get('joined', 0)} joined with replica-side records); "
        "stages sum to the client-observed TTFT"
    ]
    lines.extend(render_table(table, indent=""))
    st_table = stage_table(agg, include_mean=True)
    if len(st_table) > 1:
        lines.append("")
        lines.append("per-stage aggregate (where the fleet's TTFT goes):")
        lines.extend(render_table(st_table))
    if agg.get("top_stages"):
        lines.append("top stage by request: " + ", ".join(
            f"{s}={n}" for s, n in sorted(
                agg["top_stages"].items(), key=lambda kv: -kv[1]
            )
        ))
    return "\n".join(lines)


def _waterfall_summary(args) -> int:
    from ..telemetry.waterfall import load_router_requests, summarize_waterfall

    router_recs = load_router_requests(args.target)
    if not router_recs:
        print(
            f"no router-requests*.jsonl found under {args.target} — run the "
            "router with RouterConfig(log_dir=...) / `serve router "
            "--log-dir` to record the waterfall's router-side half",
            file=sys.stderr,
        )
        return 1
    if args.request_id is not None:
        router_recs = [r for r in router_recs
                       if _same_id(r.get("request_id"), args.request_id)]
        if not router_recs:
            print(f"request id {args.request_id} not in the router log",
                  file=sys.stderr)
            return 1
    rows = build_waterfall_rows(args.target, router_records=router_recs)
    if not rows:
        print("no request in the router log reached a first token — "
              "nothing to decompose", file=sys.stderr)
        return 1
    agg = summarize_waterfall(rows)
    if args.json:
        print(json.dumps({"waterfalls": rows, "aggregate": agg}))
    else:
        print(_format_waterfall(rows, agg))
    return 0


def _format_table(records: list, agg: dict) -> str:
    cols = ("id", "host", "slot", "prompt", "tokens", "queue_ms", "ttft_ms",
            "itl_p50_ms", "total_ms", "reason")
    rows = [cols]
    for rec in records:
        rows.append((
            str(rec.get("request_id")), str(rec.get("host", "?")),
            str(rec.get("slot")), str(rec.get("prompt_len")),
            str(rec.get("tokens")), str(rec.get("queue_wait_ms", "")),
            str(rec.get("ttft_ms", "")), str(rec.get("itl_p50_ms", "")),
            str(rec.get("total_ms", "")), str(rec.get("finish_reason", "")),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(c.rjust(w) for c, w in zip(row, widths)) for row in rows]
    lines.append("")
    lines.append(
        f"{agg['requests']} requests, {agg['tokens']} tokens; "
        + ", ".join(
            f"{k[:-len('_p50_ms')]} p50/p95/p99 = "
            f"{agg[k]}/{agg[k.replace('p50', 'p95')]}/{agg[k.replace('p50', 'p99')]} ms"
            for k in ("queue_wait_p50_ms", "ttft_p50_ms", "itl_p50_ms")
            if k in agg
        )
    )
    if "outcomes" in agg:
        parts = [f"{k}={v}" for k, v in sorted(agg["outcomes"].items())]
        if agg.get("preemptions"):
            parts.append(f"preemptions={agg['preemptions']}")
        lines.append("outcomes: " + ", ".join(parts))
    return "\n".join(lines)


def trace_command(args) -> int:
    if args.trace_cmd == "merge":
        trace = merge_traces(args.target, request_id=args.request_id)
        spans = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
        if not spans:
            what = (f"no spans for request id {args.request_id}"
                    if args.request_id is not None else "no span events")
            print(f"{what} found under {args.target}", file=sys.stderr)
            return 1
        body = json.dumps(trace)
        if args.output:
            with open(args.output, "w") as fh:
                fh.write(body)
            n = len(trace["traceEvents"])
            print(f"wrote {n} events -> {args.output} (load in Perfetto / chrome://tracing)")
        else:
            print(body)
        return 0
    if args.trace_cmd == "summary":
        if getattr(args, "waterfall", False):
            return _waterfall_summary(args)
        records = load_requests(args.target)
        if not records:
            print(f"no request records found under {args.target}", file=sys.stderr)
            return 1
        if args.request_id is not None:
            records = [r for r in records
                       if _same_id(r.get("request_id"), args.request_id)]
            if not records:
                print(f"request id {args.request_id} not in the log", file=sys.stderr)
                return 1
            if len(records) > 1:
                # one logical request, several replica hops: stitch them
                # into the hop-by-hop timeline instead of a record dump
                stitched = stitch_request(records)
                if args.json:
                    print(json.dumps({"stitched": stitched, "records": records}))
                else:
                    print(_format_stitched(stitched))
                return 0
            print(json.dumps(records[0], indent=2))
            return 0
        agg = summarize_requests(records)
        if args.json:
            print(json.dumps({"requests": records, "aggregate": agg}))
        else:
            print(_format_table(records, agg))
        return 0
    print("usage: accelerate-tpu trace {merge,summary} ...", file=sys.stderr)
    return 1


def register(subparsers):
    parser = subparsers.add_parser(
        "trace", help="Merge / inspect telemetry span traces and request logs"
    )
    sub = parser.add_subparsers(dest="trace_cmd")
    merge = sub.add_parser(
        "merge", help="Merge per-host Chrome-trace JSONLs into one trace JSON"
    )
    merge.add_argument("target", help="telemetry dir (or one trace-host*.jsonl)")
    merge.add_argument("-o", "--output", default=None, help="output path (default: stdout)")
    merge.add_argument("--request-id", default=None,
                       help="keep only this request's spans")
    summary = sub.add_parser(
        "summary", help="Summarize request-log JSONL(s) into a latency table"
    )
    summary.add_argument(
        "target", nargs="+",
        help="telemetry dir(s) (or requests-host*.jsonl files) — pass one "
             "dir per replica to merge a fleet's request logs",
    )
    summary.add_argument(
        "--request-id", default=None,
        help="print one request's full lifecycle record; with records "
             "from several replicas, stitch them into the hop-by-hop "
             "timeline",
    )
    summary.add_argument(
        "--waterfall", action="store_true",
        help="decompose each request's client-observed TTFT into stages "
             "(router-queue / placement / retry-backoff / transport / "
             "replica-queue / prefill) by joining router-requests*.jsonl "
             "with the replica request logs; prints per-stage "
             "p50/p95/p99 aggregates",
    )
    summary.add_argument("--json", action="store_true", help="machine-readable output")
    parser.set_defaults(func=trace_command)
    return parser
