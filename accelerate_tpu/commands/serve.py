"""``accelerate-tpu serve`` — launch a serving replica or the router.

Two roles, one subcommand (docs/serving.md "Multi-replica serving &
failover"):

- ``accelerate-tpu serve router --replica NAME=URL [--replica ...]``
  runs the stdlib-HTTP/JSONL front door (``serving/router.py``):
  least-loaded + session-affinity placement, failover + re-queue,
  elastic ``/v1/register`` membership. **Jax-free end to end** — the
  router tier runs on boxes with no accelerator stack, and this module
  is in the declared jax-free set (``analysis/hygiene.py``).
- ``accelerate-tpu serve replica --config tiny --port 8900`` builds a
  randomly-initialized demo model and serves it through a
  :class:`~..serving.replica_server.ReplicaServer` — the CPU-sim /
  drill bring-up path (production embedders wrap their own engine in
  ``ReplicaServer`` directly). Everything jax-heavy imports lazily
  inside the launch function, so registering the subcommand costs the
  log-reading commands nothing (the PR 12 lazy-registration pattern).
"""

from __future__ import annotations

import json


def register(subparsers):
    parser = subparsers.add_parser(
        "serve",
        help="launch a serving replica server or the multi-replica router",
    )
    sub = parser.add_subparsers(dest="role")

    router = sub.add_parser(
        "router", help="stdlib-HTTP/JSONL front door over N replicas "
                       "(jax-free; failover + re-queue + elastic membership)"
    )
    router.add_argument("--replica", action="append", default=[],
                        metavar="[NAME=]URL",
                        help="replica base URL (repeatable); more can join "
                             "at runtime via POST /v1/register")
    router.add_argument("--host", default="127.0.0.1")
    router.add_argument("--port", type=int, default=8790)
    router.add_argument("--max-inflight", type=int, default=64,
                        help="bounded router queue; past it submits shed "
                             "with shed_reason=router_queue_full")
    router.add_argument("--max-retries", type=int, default=4)
    router.add_argument("--backoff-base", type=float, default=0.05,
                        metavar="S")
    router.add_argument("--backoff-cap", type=float, default=2.0, metavar="S")
    router.add_argument("--backoff-seed", type=int, default=0)
    router.add_argument("--request-timeout", type=float, default=None,
                        metavar="S")
    router.add_argument("--poll-interval", type=float, default=0.25,
                        metavar="S", help="replica health/placement scrape "
                                          "cadence")
    router.add_argument("--no-affinity", action="store_true",
                        help="disable session->replica stickiness")
    router.add_argument("--no-kv-migration", action="store_true",
                        help="disable the KV handoff when a session moves "
                             "off a draining replica")
    router.add_argument("--log-dir", default=None, metavar="DIR",
                        help="write router-requests.jsonl (the latency "
                             "waterfall's router half), "
                             "router-decisions.jsonl (placement-decision "
                             "log) and canary-results.jsonl here")
    router.add_argument("--no-instrument", action="store_true",
                        help="disable golden-signal histograms, hop "
                             "timing stamps and the decision log (the "
                             "zero-overhead witness baseline)")
    router.add_argument("--canary-interval", type=float, default=0.0,
                        metavar="S",
                        help="probe the fleet with a seeded golden prompt "
                             "every S seconds, verifying token-exactness "
                             "(0 = off); gauges land on /metrics as "
                             "canary/*")
    router.add_argument("--canary-prompt", default="1,2,3",
                        help="comma-separated golden prompt token ids "
                             "(the first finished probe records the "
                             "golden output every later probe must "
                             "reproduce)")
    router.add_argument("--canary-max-new-tokens", type=int, default=8)
    router.add_argument("--canary-seed", type=int, default=0)

    replica = sub.add_parser(
        "replica", help="one engine process behind HTTP (demo model; "
                        "production embeds ReplicaServer over its own engine)"
    )
    replica.add_argument("--config", default="tiny",
                        help="named DecoderConfig constructor (tiny)")
    replica.add_argument("--name", default=None,
                         help="replica identity (default ATT_REPLICA or "
                              "host:port); stamped into request records")
    replica.add_argument("--host", default="127.0.0.1")
    replica.add_argument("--port", type=int, default=0,
                         help="0 binds an ephemeral port (printed as JSON "
                              "on stdout at startup)")
    replica.add_argument("--num-slots", type=int, default=4)
    replica.add_argument("--max-cache-len", type=int, default=None)
    replica.add_argument("--prefill-chunks", default="16,64",
                         help="comma-separated prefill bucket sizes")
    replica.add_argument("--page-size", type=int, default=16,
                         help="0 = flat slot arena (no paging, no prefix "
                              "cache, no KV handoff)")
    replica.add_argument("--kv-cache-dtype", default=None,
                         choices=["bf16", "int8", "int4"])
    replica.add_argument("--kv-host-entries", type=int, default=0,
                         help="host-RAM KV tier capacity in prefix entries "
                              "(0 = tiering off; evictions drop as before)")
    replica.add_argument("--kv-disk-entries", type=int, default=0,
                         help="disk KV tier capacity in prefix entries "
                              "(needs --kv-disk-dir)")
    replica.add_argument("--kv-disk-dir", default=None, metavar="DIR",
                         help="directory for demoted KV blobs (durable "
                              "across restarts; torn/corrupt blobs are "
                              "rejected and deleted)")
    replica.add_argument("--kv-peers", action="append", default=[],
                         metavar="[NAME=]URL",
                         help="peer replica base URL for the fleet KV tier "
                              "(repeatable): a local miss pulls a warm "
                              "prefix over /v1/kv/export after checking "
                              "the peer's /v1/kv/directory")
    replica.add_argument("--temperature", type=float, default=0.0)
    replica.add_argument("--top-k", type=int, default=None)
    replica.add_argument("--steps-per-call", type=int, default=1)
    replica.add_argument("--init-seed", type=int, default=0,
                         help="model-init PRNG seed (two replicas launched "
                              "with the same config+seed serve the same "
                              "weights — what the drills rely on)")
    replica.add_argument("--max-seq-len", type=int, default=256)

    parser.set_defaults(func=serve_command)


def serve_command(args) -> int:
    role = getattr(args, "role", None)
    if role == "router":
        return _serve_router(args)
    if role == "replica":
        return _serve_replica(args)
    print("usage: accelerate-tpu serve {router|replica} [--help]")
    return 1


def _parse_replica_flags(values) -> list:
    pairs = []
    for i, item in enumerate(values):
        if "=" in item:
            name, url = item.split("=", 1)
        else:
            name, url = f"r{i}", item
        pairs.append((name.strip(), url.strip()))
    return pairs


def _serve_router(args) -> int:
    # jax-free by construction: router.py + telemetry.fleet only
    from ..serving.router import Router, RouterConfig, RouterServer

    cfg = RouterConfig(
        max_inflight=args.max_inflight,
        max_retries=args.max_retries,
        backoff_base_s=args.backoff_base,
        backoff_cap_s=args.backoff_cap,
        backoff_seed=args.backoff_seed,
        request_timeout_s=args.request_timeout,
        poll_interval_s=args.poll_interval,
        affinity=not args.no_affinity,
        migrate_session_kv=not args.no_kv_migration,
        instrument=not args.no_instrument,
        log_dir=args.log_dir,
    )
    router = Router(_parse_replica_flags(args.replica), config=cfg).start()
    if args.canary_interval and args.canary_interval > 0:
        from ..telemetry.canary import CanaryProber, flight_via_router, via_router

        prompt = [int(t) for t in str(args.canary_prompt).split(",") if t.strip()]
        prober = CanaryProber(
            via_router(router),
            [{"prompt": prompt, "seed": int(args.canary_seed),
              "max_new_tokens": int(args.canary_max_new_tokens)}],
            interval_s=float(args.canary_interval),
            log_dir=args.log_dir,
            flight_fn=flight_via_router(router),
        ).start()
        router.attach_canary(prober)
    server = RouterServer(router, host=args.host, port=args.port)
    print(json.dumps({"role": "router", "port": server.port,
                      "replicas": len(args.replica),
                      "canary": bool(args.canary_interval),
                      "log_dir": args.log_dir}), flush=True)
    try:
        import time

        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        router.close()
    return 0


def build_replica_engine(args):
    """Build the demo engine the ``replica`` role serves (also what the
    multi-process drills import to construct a token-exact reference in
    the test process: same config + ``--init-seed`` => same weights).
    This is the jax-paying half — import it lazily."""
    import jax

    from ..models import DecoderConfig, DecoderLM
    from ..parallel.sharding import unbox_params
    from ..serving.engine import ServingEngine

    if args.config != "tiny":
        raise SystemExit(f"unknown --config {args.config!r} (have: tiny)")
    cfg = DecoderConfig.tiny(max_seq_len=int(args.max_seq_len))
    model = DecoderLM(cfg)
    variables = model.init_variables(
        jax.random.PRNGKey(int(args.init_seed)), batch_size=1, seq_len=16
    )
    params, _ = unbox_params(variables["params"])
    chunks = tuple(
        int(c) for c in str(args.prefill_chunks).split(",") if c.strip()
    )
    page_size = int(args.page_size) or None
    kv_tiers = None
    host_entries = int(getattr(args, "kv_host_entries", 0) or 0)
    disk_entries = int(getattr(args, "kv_disk_entries", 0) or 0)
    peers = _parse_replica_flags(getattr(args, "kv_peers", []) or [])
    if page_size and (host_entries or disk_entries or peers):
        from ..serving.tiers import TierConfig

        kv_tiers = TierConfig(
            host_entries=max(host_entries, 1 if (disk_entries or peers) else 0),
            disk_entries=disk_entries,
            disk_dir=getattr(args, "kv_disk_dir", None),
            peers=tuple(peers),
        )
    return ServingEngine(
        model, params,
        num_slots=int(args.num_slots),
        max_cache_len=args.max_cache_len,
        prefill_chunks=chunks,
        page_size=page_size,
        temperature=float(args.temperature),
        top_k=args.top_k,
        steps_per_call=int(args.steps_per_call),
        kv_cache_dtype=args.kv_cache_dtype,
        replica=args.name,
        kv_tiers=kv_tiers,
    )


def _serve_replica(args) -> int:
    from ..serving.replica_server import ReplicaServer

    engine = build_replica_engine(args)
    engine.warmup()
    engine.mark_steady()
    server = ReplicaServer(
        engine, host=args.host, port=int(args.port), name=args.name,
        handle_signals=True,
    ).start()
    print(json.dumps({"role": "replica", "replica": server.name,
                      "port": server.port, "url": server.url}), flush=True)
    try:
        # SIGTERM drains (finish in-flight, flight-record) and unblocks
        # this wait; SIGKILL is what the drills practice surviving
        server.serve_until_drained()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0
