"""`accelerate-tpu report` — the doctor's read of a telemetry dir.

`trace` answers "show me the timeline"; `report` answers "where did the
time go and why". It merges everything a session leaves behind —

    goodput-host<i>.json     wall-clock partition (the goodput ledger)
    costs-host<i>.json       per-executable roofline rows (cost registry)
    forensics-host<i>.jsonl  diagnosed recompiles with their causes
    metrics-host<i>.jsonl    per-step records (optional)
    requests-host<i>.jsonl   serving request log (optional)

    timeline-host<i>.jsonl   continuous gauge timeline (sampled rollups)
    alerts-host<i>.jsonl     alert lifecycle events (pending/firing/resolved)
    usage-host<i>.json       per-tenant usage accounting
    router-requests*.jsonl   router request log (waterfall's router half)
    canary-results.jsonl     synthetic canary probe outcomes
    audit.json               static-audit findings (`accelerate-tpu audit --out`)
    loadtest-scorecard.json  SLO scorecard (`accelerate-tpu loadtest --out`)

— into one explanation:

    accelerate-tpu report runs/exp/telemetry
    accelerate-tpu report runs/exp/telemetry --json

The text form prints the goodput breakdown (fractions sum to 1.0), the
top executables by measured wall with their roofline class and cost-model
MFU / bandwidth utilization, every recompile with the exact argument and
aval change that caused it, the timeline's headline series, the alert
history, and the per-tenant usage table. Pure stdlib + the telemetry
host modules: no jax import, so it runs anywhere the artifacts land.

``--diff A B`` is the regression sentry: it flattens two runs' metrics
(telemetry dirs, dirs holding ``BENCH_r*.json``, or bench JSON files
directly) and flags every shared metric that moved more than
``--threshold`` — turning the bench trajectory into a checkable
artifact (``--fail`` exits non-zero when anything is flagged).
"""

from __future__ import annotations

import glob
import json
import os
import sys

BAR_WIDTH = 24


def _host_files(target: str, pattern: str) -> list:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, pattern)))
    return []


def _host_of(path: str, prefix: str) -> str:
    name = os.path.basename(path)
    stem = name.split(".", 1)[0]
    return stem[len(prefix):] if stem.startswith(prefix) else "?"


def _load_json(path: str):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _load_jsonl(path: str) -> list:
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    except (OSError, ValueError):
        pass
    return out


def load_goodput(target: str) -> dict:
    """Merged goodput: per-host snapshots plus an aggregate over summed
    bucket seconds (an idle host dilutes fleet goodput — that is the
    point of fleet accounting)."""
    from ..telemetry.goodput import BUCKETS

    hosts = {}
    for path in _host_files(target, "goodput-host*.json"):
        data = _load_json(path)
        if data:
            hosts[_host_of(path, "goodput-host")] = data
    if not hosts:
        return {}
    seconds = {b: 0.0 for b in BUCKETS}
    elapsed = 0.0
    for data in hosts.values():
        elapsed += data.get("elapsed_s") or 0.0
        for b in BUCKETS:
            seconds[b] += (data.get("seconds") or {}).get(b) or 0.0
    total = sum(seconds.values())
    fractions = {b: (seconds[b] / total if total > 0 else 0.0) for b in BUCKETS}
    return {"hosts": hosts, "seconds": seconds, "fractions": fractions,
            "elapsed_s": elapsed}


def load_costs(target: str) -> dict:
    """Merged cost registry: rows keyed by executable name, wall/calls
    summed across hosts, static cost fields from the first host that
    captured them."""
    merged: dict = {}
    peaks = {}
    for path in _host_files(target, "costs-host*.json"):
        data = _load_json(path)
        if not data:
            continue
        for key in ("peak_flops", "peak_hbm_bw", "ridge_intensity"):
            if data.get(key) and key not in peaks:
                peaks[key] = data[key]
        for row in data.get("executables") or []:
            name = row.get("name")
            if name is None:
                continue
            cur = merged.get(name)
            if cur is None:
                merged[name] = dict(row)
            else:
                cur["wall_s"] = round(cur.get("wall_s", 0.0) + (row.get("wall_s") or 0.0), 4)
                cur["calls"] = cur.get("calls", 0) + (row.get("calls") or 0)
                # dynamic rows (per-call cost varies with runtime state —
                # the paged decode kernel) merge by TOTALS, not by the
                # first host's per-call average
                for key in ("flops_total", "hbm_bytes_total"):
                    if row.get(key) is not None or cur.get(key) is not None:
                        cur[key] = (cur.get(key) or 0.0) + (row.get(key) or 0.0)
                for k, v in row.items():
                    cur.setdefault(k, v)
    rows = sorted(merged.values(), key=lambda r: -(r.get("wall_s") or 0.0))
    # re-derive the utilization numbers over the merged wall
    pf, pb = peaks.get("peak_flops"), peaks.get("peak_hbm_bw")
    for row in rows:
        if row.get("dynamic") and row.get("calls"):
            for total, per_call in (("flops_total", "flops_per_call"),
                                    ("hbm_bytes_total", "hbm_bytes_per_call")):
                if row.get(total) is not None:
                    row[per_call] = row[total] / row["calls"]
            # AI / roofline class must come from the merged totals too, or
            # the row would pair fleet-total throughput numbers with host
            # 0's classification
            if row.get("flops_total") and row.get("hbm_bytes_total"):
                ai = row["flops_total"] / row["hbm_bytes_total"]
                row["arith_intensity"] = round(ai, 4)
                ridge = row.get("ridge_intensity") or peaks.get("ridge_intensity")
                if ridge:
                    row["roofline"] = (
                        "compute-bound" if ai >= ridge else "memory-bound"
                    )
        wall, calls = row.get("wall_s") or 0.0, row.get("calls") or 0
        if wall > 0 and calls > 0:
            if row.get("flops_per_call") and pf:
                row["mfu_model_pct"] = round(
                    100.0 * row["flops_per_call"] * calls / wall / pf, 3)
            if row.get("hbm_bytes_per_call"):
                row["hbm_gbps"] = round(
                    row["hbm_bytes_per_call"] * calls / wall / 1e9, 3)
                if pb:
                    row["bw_util_pct"] = round(
                        100.0 * row["hbm_bytes_per_call"] * calls / wall / pb, 3)
    return {**peaks, "executables": rows}


def load_forensics(target: str) -> list:
    """Every forensics record (host-tagged, oldest first)."""
    out = []
    for path in _host_files(target, "forensics-host*.jsonl"):
        host = _host_of(path, "forensics-host")
        for rec in _load_jsonl(path):
            rec.setdefault("host", host)
            out.append(rec)
    out.sort(key=lambda r: r.get("time_unix_s", 0))
    return out


def load_steps(target: str) -> dict:
    """Aggregate of the per-step metrics JSONL (when the run wrote one)."""
    walls, tokens, compiles = [], 0, 0
    for path in _host_files(target, "metrics-host*.jsonl"):
        for rec in _load_jsonl(path):
            if rec.get("wall_s"):
                walls.append(float(rec["wall_s"]) / max(int(rec.get("steps", 1)), 1))
            tokens += rec.get("tokens") or 0
            compiles += rec.get("compile_events") or 0
    if not walls:
        return {}
    walls.sort()
    return {
        "steps": len(walls),
        "step_time_p50_s": round(walls[len(walls) // 2], 4),
        "step_time_max_s": round(walls[-1], 4),
        "tokens": tokens,
        "compile_events": compiles,
    }


# the series the text report (and `watch`) treat as headliners — shown
# first when present; every other sampled key stays in --json
NOTABLE_TIMELINE_KEYS = (
    "serving/tokens_per_s", "serving/itl_recent_p99_ms",
    "serving/ttft_p99_ms", "serving/queue_depth", "serving/slot_occupancy",
    "serving/pages_in_use", "serving/shed", "goodput/goodput_frac",
    "serving/capacity_tokens_per_s", "serving/headroom_frac",
    "sys/tokens_per_s", "sys/mfu_pct", "alerts/firing_count",
)


def load_timeline_summary(target: str) -> dict:
    """Full-span stats per sampled gauge out of ``timeline-host*.jsonl``
    (merged across hosts): {samples, span_s, keys: {key: {last, mean,
    min, max, n}}}."""
    if not _host_files(target, "timeline-host*.jsonl"):
        return {}
    from ..telemetry.timeline import load_timeline

    tl = load_timeline(target)
    if tl.sample_count == 0 or tl.last_t is None:
        return {}
    keys = {}
    span = 0.0
    for key in tl.keys():
        w = tl.window(key, float("inf"), now=tl.last_t)
        if not w:
            continue
        span = max(span, w["span_s"])
        keys[key] = {
            "last": round(w["last"], 4),
            "mean": round(w["mean"], 4) if w["mean"] is not None else None,
            "min": round(w["min"], 4),
            "max": round(w["max"], 4),
            "n": w["n"],
        }
    return {"samples": tl.sample_count, "span_s": round(span, 1), "keys": keys}


def load_alert_summary(target: str) -> dict:
    """Alert history out of ``alerts-host*.jsonl`` (and the fleet
    collector's ``alerts-fleet.jsonl``): per-rule final state +
    fired/resolved counts, plus the raw event list."""
    if not (_host_files(target, "alerts-host*.jsonl")
            or _host_files(target, "alerts-fleet.jsonl")):
        return {}
    from ..telemetry.alerts import load_alerts

    return load_alerts(target)


def load_usage_table(target: str) -> dict:
    if not _host_files(target, "usage-host*.json"):
        return {}
    from ..telemetry.usage import load_usage

    return load_usage(target)


def load_fleet_summary(target: str) -> dict:
    """Fleet-collector artifacts (``fleet.json`` snapshot +
    ``fleet-events.jsonl`` health transitions) under the telemetry dir —
    present when a :class:`~..telemetry.fleet.FleetCollector` ran with
    ``log_dir`` pointed here."""
    if not (_host_files(target, "fleet.json")
            or _host_files(target, "fleet-events.jsonl")):
        return {}
    from ..telemetry.fleet import load_fleet

    return load_fleet(target)


def load_waterfall_summary(target: str) -> dict:
    """Per-stage TTFT decomposition aggregate — present when a
    ``Router(log_dir=...)`` left ``router-requests*.jsonl`` here
    (replica request logs join in when they share the dir)."""
    if not _host_files(target, "router-requests*.jsonl"):
        return {}
    from ..telemetry.waterfall import summarize_waterfall
    from .trace import build_waterfall_rows

    rows = build_waterfall_rows(target)
    return summarize_waterfall(rows) if rows else {}


def load_canary_summary(target: str) -> dict:
    """Canary probe outcomes out of ``canary-results.jsonl``: totals,
    recent pass ratio, and the replicas that served failing probes."""
    if not _host_files(target, "canary-results.jsonl"):
        return {}
    from ..telemetry.canary import load_canary

    results = load_canary(target)
    if not results:
        return {}
    failed = [r for r in results if not r.get("passed")]
    by_replica: dict = {}
    for r in failed:
        # a probe that never reached a replica (router down, submit_fn
        # error) has no attribution — say so, don't render "None"
        name = r.get("replica") or "(unattributed)"
        by_replica[str(name)] = by_replica.get(str(name), 0) + 1
    recent = results[-32:]
    return {
        "probes": len(results),
        "passed": sum(1 for r in results if r.get("passed")),
        "failed": len(failed),
        "pass_ratio": round(
            sum(1 for r in recent if r.get("passed")) / len(recent), 4
        ),
        "failing_replicas": by_replica,
        "last_failure": failed[-1] if failed else None,
    }


def load_audit(target: str) -> dict:
    """The static-audit snapshot (``audit.json`` written by
    ``accelerate-tpu audit --out DIR``): active findings, baselined
    suppressions, and the severity summary."""
    for path in _host_files(target, "audit.json"):
        data = _load_json(path)
        if isinstance(data, dict):
            return data
    return {}


def load_autoscale_summary(target: str) -> dict:
    """Autoscaler decision history out of ``autoscale-decisions.jsonl``:
    counts by action and outcome, reaction times (burn-rule firing →
    first verified token on the new replica), the scale-in conservation
    verdicts, and the recent decisions with their stage decomposition."""
    if not _host_files(target, "autoscale-decisions.jsonl"):
        return {}
    from ..serving.autoscaler import load_autoscale_decisions

    records = load_autoscale_decisions(target)
    if not records:
        return {}
    actions: dict = {}
    outcomes: dict = {}
    for r in records:
        act = str(r.get("action"))
        actions[act] = actions.get(act, 0) + 1
        out = r.get("outcome")
        if out:
            outcomes[str(out)] = outcomes.get(str(out), 0) + 1
    reactions = [r["autoscale_reaction_s"] for r in records
                 if isinstance(r.get("autoscale_reaction_s"), (int, float))]
    not_conserved = sum(
        1 for r in records
        if (r.get("ledger") or {}).get("conserved") is False
    )
    return {
        "decisions": len(records),
        "actions": actions,
        "outcomes": outcomes,
        "reaction_s_last": round(reactions[-1], 4) if reactions else None,
        "reaction_s_max": round(max(reactions), 4) if reactions else None,
        "scale_ins_not_conserved": not_conserved,
        "recent": records[-8:],
    }


def load_incident_summary(target: str) -> dict:
    """Reconstructed incidents out of the alert logs: counts, still-open
    tally, mean resolved duration, and a one-line digest per incident —
    the teaser the full ``accelerate-tpu incident show`` expands."""
    if not (_host_files(target, "alerts-host*.jsonl")
            or _host_files(target, "alerts-fleet.jsonl")):
        return {}
    from ..telemetry.incidents import reconstruct_incidents, summarize_incidents

    incidents = reconstruct_incidents(target)
    if not incidents:
        return {}
    out = summarize_incidents(incidents)
    out["recent"] = [
        {
            "index": i["index"], "rule": i["rule"], "state": i["state"],
            "fired_t": i["fired_t"], "duration_s": i["duration_s"],
            "exemplars": i["exemplars"][:3],
            "top_stages": sorted(set(
                r["top_stage"] for r in i.get("exemplar_requests") or []
                if r.get("top_stage")
            )),
            "events": len(i.get("events") or []),
        }
        for i in incidents[-8:]
    ]
    return out


def load_loadtest_scorecard(target: str) -> dict:
    """The SLO scorecard (``loadtest-scorecard.json`` written by
    ``accelerate-tpu loadtest --out DIR``): attainment per tenant and
    fleet-wide, goodput tokens/s-per-chip, the conservation ledger."""
    if not _host_files(target, "loadtest-scorecard.json"):
        return {}
    from ..telemetry.scorecard import load_scorecard

    return load_scorecard(target) or {}


def load_report(target: str) -> dict:
    forensics = load_forensics(target)
    data = {
        "target": target,
        "goodput": load_goodput(target),
        "costs": load_costs(target),
        "recompiles": [r for r in forensics if r.get("event") == "recompile"],
        "first_compiles": [r for r in forensics
                           if r.get("event") == "first_compile"],
        "steps": load_steps(target),
        "timeline": load_timeline_summary(target),
        "alerts": load_alert_summary(target),
        "usage": load_usage_table(target),
        "fleet": load_fleet_summary(target),
        "waterfall": load_waterfall_summary(target),
        "canary": load_canary_summary(target),
        "autoscale": load_autoscale_summary(target),
        "incidents": load_incident_summary(target),
        "audit": load_audit(target),
        "loadtest": load_loadtest_scorecard(target),
    }
    req_files = _host_files(target, "requests-host*.jsonl")
    if req_files:
        from .trace import load_requests, summarize_requests

        data["requests"] = summarize_requests(load_requests(target))
    return data


def _bar(frac: float) -> str:
    n = int(round(max(0.0, min(frac, 1.0)) * BAR_WIDTH))
    return "#" * n + "." * (BAR_WIDTH - n)


def render_table(rows, indent: str = "  ") -> list:
    """Column-aligned text lines for a [header, *rows] tuple list (the
    one table renderer every section — and `watch` — shares)."""
    rows = [tuple(str(c) for c in r) for r in rows]
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return [indent + "  ".join(c.ljust(w) for c, w in zip(r, widths))
            for r in rows]


def format_report(data: dict) -> str:
    lines = [f"== accelerate-tpu report: {data.get('target', '?')} =="]

    gp = data.get("goodput") or {}
    if gp:
        fr = gp["fractions"]
        lines.append("")
        lines.append(
            f"goodput breakdown ({len(gp.get('hosts') or {})} host(s), "
            f"{gp.get('elapsed_s', 0):.1f}s wall; fractions sum to "
            f"{sum(fr.values()):.2f}):"
        )
        order = ("compute", "compile", "checkpoint", "data_wait", "stall", "idle")
        for b in order:
            f = fr.get(b, 0.0)
            secs = (gp.get("seconds") or {}).get(b, 0.0)
            lines.append(f"  {b:<10} {100 * f:6.1f}%  {_bar(f)}  {secs:9.2f}s")
        lines.append(f"  goodput (productive compute) = {100 * fr.get('compute', 0.0):.1f}%")
    else:
        lines.append("")
        lines.append("goodput breakdown: no goodput-host*.json found "
                     "(run with telemetry enabled)")

    costs = data.get("costs") or {}
    rows = costs.get("executables") or []
    lines.append("")
    if rows:
        ridge = costs.get("ridge_intensity")
        ridge_txt = f"{ridge:.1f}" if isinstance(ridge, (int, float)) else "?"
        lines.append("top executables by measured wall (roofline vs "
                     f"ridge {ridge_txt} flops/byte):")
        header = ("executable", "wall_s", "calls", "class", "AI",
                  "MFU(model)", "BW util", "GB/s")
        table = [header]
        for row in rows[:10]:
            mfu = row.get("mfu_model_pct")
            bw = row.get("bw_util_pct")
            gbps = row.get("hbm_gbps")
            table.append((
                str(row.get("name")),
                f"{row.get('wall_s', 0.0):.3f}" if row.get("wall_s") is not None else "",
                str(row.get("calls", "")),
                row.get("roofline", "?"),
                f"{row['arith_intensity']:.2f}" if row.get("arith_intensity") is not None else "",
                f"{mfu:.2f}%" if mfu is not None else "",
                f"{bw:.2f}%" if bw is not None else "",
                f"{gbps:.1f}" if gbps is not None else "",
            ))
        lines.extend(render_table(table))
    else:
        lines.append("executables: no costs-host*.json found")

    recs = data.get("recompiles") or []
    firsts = data.get("first_compiles") or []
    lines.append("")
    lines.append(f"recompiles ({len(recs)} diagnosed, "
                 f"{len(firsts)} first compiles):")
    for rec in recs:
        t = rec.get("time_unix_s")
        comp = rec.get("compile_s")
        hits = rec.get("compile_cache_hits") or 0
        suffix = []
        if comp is not None:
            suffix.append(f"compile {comp:.2f}s")
        suffix.append(f"{rec.get('compile_events', '?')} events")
        if hits:
            suffix.append(f"{hits} cache hits")
        stamp = f"[host {rec.get('host', '?')}" + (
            f" @{t:.0f}] " if isinstance(t, (int, float)) else "] ")
        lines.append(f"  {stamp}{rec.get('cause')}  ({', '.join(suffix)})")
    if not recs:
        lines.append("  none — every entry point held its steady-state signature")

    steps = data.get("steps") or {}
    if steps:
        lines.append("")
        lines.append(
            f"steps: {steps['steps']} recorded, p50 {steps['step_time_p50_s']}s, "
            f"max {steps['step_time_max_s']}s, {steps['tokens']} tokens, "
            f"{steps['compile_events']} compile events"
        )
    req = data.get("requests") or {}
    if req.get("requests"):
        lines.append(
            f"serving: {req.get('requests')} requests, {req.get('tokens')} tokens"
            + (f", ttft p50/p99 = {req.get('ttft_p50_ms')}/{req.get('ttft_p99_ms')} ms"
               if req.get("ttft_p50_ms") is not None else "")
        )

    tl = data.get("timeline") or {}
    if tl.get("samples"):
        lines.append("")
        lines.append(
            f"timeline: {tl['samples']} samples over {tl.get('span_s', 0)}s "
            "(timeline-host*.jsonl)"
        )
        keys = tl.get("keys") or {}
        shown = [k for k in NOTABLE_TIMELINE_KEYS if k in keys]
        for key in shown:
            s = keys[key]
            lines.append(
                f"  {key:<32} last {s['last']:>10}  mean {s['mean']:>10}  "
                f"max {s['max']:>10}"
            )
        rest = len(keys) - len(shown)
        if rest > 0:
            lines.append(f"  (+{rest} more sampled series in --json)")

    alerts = data.get("alerts") or {}
    rules = alerts.get("rules") or {}
    if rules:
        firing = sorted(n for n, r in rules.items() if r.get("state") == "firing")
        fired_total = sum(r.get("fired_count", 0) for r in rules.values())
        lines.append("")
        lines.append(
            f"alerts: {len(firing)} firing, {fired_total} fired over the "
            f"session ({len(alerts.get('events') or [])} lifecycle events)"
        )
        for name in sorted(rules, key=lambda n: (rules[n].get("state") != "firing", n)):
            r = rules[name]
            lines.append(
                f"  [{r.get('state', '?'):>7}] {name}  fired {r.get('fired_count', 0)}x"
                + (f", last value {r.get('last_value')}"
                   if r.get("last_value") is not None else "")
            )

    fleet = data.get("fleet") or {}
    replicas = fleet.get("replicas") or {}
    if replicas:
        gauges = fleet.get("fleet") or {}
        down = gauges.get("fleet/replicas_down", 0)
        lines.append("")
        lines.append(
            f"fleet: {len(replicas)} replica(s), "
            f"{gauges.get('fleet/replicas_placeable', '?')} placeable, "
            f"{down} down ({fleet.get('polls', '?')} polls)"
        )
        header = ("replica", "state", "load_score", "scrapes_ok",
                  "scrapes_failed", "last_ok_age_s")
        table = [header]
        placement = fleet.get("placement") or []
        order = [p["replica"] for p in placement if p["replica"] in replicas]
        order += [n for n in sorted(replicas) if n not in order]
        for name in order:
            r = replicas[name]
            score = r.get("load_score")
            table.append((
                name, r.get("state", "?"),
                f"{score:.3f}" if isinstance(score, float) else str(score),
                str(r.get("scrapes_ok", "")), str(r.get("scrapes_failed", "")),
                str(r.get("last_ok_age_s", "")),
            ))
        lines.extend(render_table(table))
        events = fleet.get("events") or []
        if events:
            lines.append(f"  health transitions ({len(events)}):")
            for evt in events[-8:]:
                lines.append(
                    f"    @{evt.get('t_unix_s', 0):.0f} {evt.get('replica')}: "
                    f"{evt.get('from')} -> {evt.get('to')} ({evt.get('reason')})"
                )

    wf = data.get("waterfall") or {}
    if wf.get("requests"):
        from ..telemetry.waterfall import stage_table

        lines.append("")
        lines.append(
            f"request waterfall ({wf['requests']} request(s), "
            f"{wf.get('joined', 0)} joined with replica records"
            + (f"; e2e TTFT p50/p99 = {wf['e2e_ttft_p50_ms']}/"
               f"{wf['e2e_ttft_p99_ms']} ms"
               if wf.get("e2e_ttft_p99_ms") is not None else "")
            + "):"
        )
        lines.extend(render_table(stage_table(wf)))

    canary = data.get("canary") or {}
    if canary.get("probes"):
        lines.append("")
        lines.append(
            f"canary: {canary['probes']} probe(s), {canary['failed']} "
            f"failed, recent pass ratio {canary['pass_ratio']}"
        )
        for name, n in sorted((canary.get("failing_replicas") or {}).items(),
                              key=lambda kv: -kv[1]):
            lines.append(f"  failing probes served by {name}: {n}")
        last = canary.get("last_failure")
        if last:
            lines.append(
                f"  last failure: {last.get('request_id')} on "
                f"{last.get('replica')} ({last.get('reason', '?')})"
            )

    a = data.get("autoscale") or {}
    if a.get("decisions"):
        acts = a.get("actions") or {}
        lines.append("")
        lines.append(
            f"autoscale: {a['decisions']} decision(s) — "
            f"{acts.get('scale_out', 0)} out, {acts.get('scale_in', 0)} in, "
            f"{acts.get('hold', 0)} held"
            + (f"; reaction last/max = {a['reaction_s_last']}/"
               f"{a['reaction_s_max']} s"
               if a.get("reaction_s_last") is not None else "")
        )
        if a.get("scale_ins_not_conserved"):
            lines.append(
                f"  [NOT CONSERVED] {a['scale_ins_not_conserved']} "
                "scale-in(s) lost requests across the membership change"
            )
        for rec in (a.get("recent") or [])[-6:]:
            stages = rec.get("stages") or {}
            stage_txt = " ".join(
                f"{k.replace('_s', '')}={v:.2f}s" for k, v in stages.items()
                if isinstance(v, (int, float))
            )
            lines.append(
                f"  @{rec.get('t_unix_s', 0):.0f} {rec.get('action')}"
                + (f" {rec.get('replica')}" if rec.get("replica") else "")
                + f" [{rec.get('outcome') or rec.get('reason', '?')}]"
                + f" ({rec.get('reason', '?')})"
                + (f"  {stage_txt}" if stage_txt else "")
            )

    inc = data.get("incidents") or {}
    if inc.get("count"):
        dur = (f', mean duration {inc["mean_duration_s"]:.1f}s'
               if inc.get("mean_duration_s") is not None else "")
        lines.append("")
        lines.append(
            f'incidents: {inc["count"]} reconstructed, {inc["open"]} open'
            f'{dur} (`accelerate-tpu incident show <dir>` for the timeline)'
        )
        for row in inc.get("recent") or []:
            ex = ",".join(str(r) for r in row.get("exemplars") or []) or "-"
            top = "/".join(row.get("top_stages") or []) or "?"
            d = (f'{row["duration_s"]:.1f}s'
                 if row.get("duration_s") is not None else "open")
            lines.append(
                f'  #{row["index"]} {row["rule"]} [{row["state"]}] '
                f'dur={d} events={row.get("events", 0)} '
                f'exemplars={ex} dominant={top}'
            )

    card = data.get("loadtest") or {}
    if card:
        from ..telemetry.scorecard import format_scorecard

        lines.append("")
        lines.append("loadtest scorecard:")
        lines.extend("  " + ln for ln in format_scorecard(card))

    usage = data.get("usage") or {}
    tenants = usage.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append(f"tenant usage ({len(tenants)} tenant(s), "
                     f"{usage.get('hosts', 1)} host(s)):")
        cols = ("prefill_tokens", "decode_tokens", "prefix_hit_tokens",
                "page_seconds", "compute_ms", "finished", "shed",
                "preempted", "cancelled")
        header = ("tenant",) + tuple(c.replace("_tokens", "_tok") for c in cols)
        table = [header]
        order = sorted(tenants, key=lambda t: -(tenants[t].get("decode_tokens") or 0))
        for name in order:
            row = tenants[name]
            table.append((name,) + tuple(
                f"{row.get(c, 0):.1f}" if isinstance(row.get(c), float)
                else str(row.get(c, 0)) for c in cols
            ))
        lines.extend(render_table(table))

    audit = data.get("audit") or {}
    if audit:
        summ = audit.get("summary") or {}
        # severity-major before truncating: a P1 must never hide behind
        # twelve P2s in discovery order
        sev_rank = {"P1": 0, "P2": 1, "P3": 2}
        active = sorted(
            audit.get("findings") or [],
            key=lambda f: (sev_rank.get(f.get("severity"), 9),
                           str(f.get("target")), str(f.get("check"))),
        )
        suppressed = audit.get("suppressed") or []
        lines.append("")
        lines.append(
            f"static audit: {summ.get('findings_total', len(active))} active "
            f"finding(s) ({summ.get('findings_p1', 0)} P1), "
            f"{len(suppressed)} baselined"
        )
        for f in active[:12]:
            lines.append(
                f"  [{f.get('severity', '?')}] {f.get('check')}  "
                f"{f.get('target')}  ({f.get('fingerprint', '?')})"
            )
            lines.append(f"       {f.get('message', '')}")
        if len(active) > 12:
            lines.append(f"  (+{len(active) - 12} more in --json)")
        for f in suppressed[:6]:
            lines.append(
                f"  [baselined {f.get('severity', '?')}] {f.get('check')}  "
                f"{f.get('target')}: {f.get('justification', '?')}"
            )
    return "\n".join(lines)


# -- the regression sentry (`report --diff A B`) ----------------------------


def _flatten_numeric(obj, prefix: str, out: dict):
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten_numeric(v, f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(obj, bool):
        return
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)


def _bench_metrics(path: str) -> dict:
    """Flat metrics from one BENCH_r*.json (the driver's shape: headline
    `parsed.metric/value` plus the `parsed.extra` tree) or any plain
    metric-tree JSON."""
    data = _load_json(path)
    if not isinstance(data, dict):
        return {}
    out: dict = {}
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        if isinstance(parsed.get("value"), (int, float)):
            out[str(parsed["metric"])] = float(parsed["value"])
        _flatten_numeric(parsed.get("extra") or {}, "", out)
    else:
        _flatten_numeric(data, "", out)
    # per-attempt lists and wall-clock stamps are noise, not metrics
    return {k: v for k, v in out.items()
            if not k.endswith(("_attempts", "time_unix_s"))}


def collect_diff_metrics(target: str) -> dict:
    """One side of a diff, flattened to {metric: float}: a bench JSON
    file, a dir holding ``BENCH_r*.json`` (newest wins), or a telemetry
    artifact dir (goodput fractions, roofline rows, request/step
    summaries, timeline means, usage totals)."""
    if os.path.isfile(target):
        return _bench_metrics(target)
    bench = sorted(glob.glob(os.path.join(target, "BENCH_r*.json")))
    if bench:
        return _bench_metrics(bench[-1])
    data = load_report(target)
    out: dict = {}
    for b, f in (data["goodput"].get("fractions") or {}).items():
        out[f"goodput/{b}_frac"] = float(f)
    for row in data["costs"].get("executables") or []:
        name = row.get("name")
        for field in ("mfu_model_pct", "bw_util_pct", "hbm_gbps", "arith_intensity"):
            if isinstance(row.get(field), (int, float)):
                out[f"exe/{name}/{field}"] = float(row[field])
    _flatten_numeric(data.get("steps") or {}, "steps", out)
    _flatten_numeric(data.get("requests") or {}, "requests", out)
    for key, s in ((data.get("timeline") or {}).get("keys") or {}).items():
        if isinstance(s.get("mean"), (int, float)):
            out[f"timeline/{key}/mean"] = float(s["mean"])
    for tenant, row in ((data.get("usage") or {}).get("tenants") or {}).items():
        _flatten_numeric(row, f"usage/{tenant}", out)
    # the edge regression signals: per-stage waterfall percentiles (a p99
    # that moved names its stage) and the canary pass ratio (any drop is
    # a correctness regression — diff_metrics flags it past-threshold-or-not)
    wf = data.get("waterfall") or {}
    for stage, row in (wf.get("stages") or {}).items():
        for field in ("p50_ms", "p99_ms"):
            if isinstance(row.get(field), (int, float)):
                out[f"waterfall/{stage}/{field}"] = float(row[field])
    if isinstance(wf.get("e2e_ttft_p99_ms"), (int, float)):
        out["router_e2e_ttft_p99_ms"] = float(wf["e2e_ttft_p99_ms"])
    # which prefill path served the joined requests: a round where
    # `waterfall/prefill_kernel_dense` grows at `_ragged`'s expense is a
    # kernel-gate regression even if the p99 hasn't moved yet. (Bench-side
    # `prefill_kernel_speedup` / `prefill_pad_waste_frac` need no code
    # here — `_flatten_numeric` lifts every numeric in the bench extras.)
    for mode, count in (wf.get("prefill_kernel") or {}).items():
        if isinstance(count, (int, float)) and not isinstance(count, bool):
            out[f"waterfall/prefill_kernel_{mode}"] = float(count)
    canary = data.get("canary") or {}
    if isinstance(canary.get("pass_ratio"), (int, float)):
        out["canary_pass_ratio"] = float(canary["pass_ratio"])
    # the closed-loop signals: scale action counts and the reaction time
    # (burn firing -> first verified token on the new replica) — a round
    # where reaction_s grew names the actuation path, and any scale-in
    # that broke conservation is a correctness regression outright
    autoscale = data.get("autoscale") or {}
    if autoscale:
        acts = autoscale.get("actions") or {}
        out["autoscale/scale_outs"] = float(acts.get("scale_out", 0))
        out["autoscale/scale_ins"] = float(acts.get("scale_in", 0))
        for field in ("reaction_s_last", "reaction_s_max",
                      "scale_ins_not_conserved"):
            if isinstance(autoscale.get(field), (int, float)):
                out[f"autoscale/{field}"] = float(autoscale[field])
    # the replay-plane regression signals: fleet attainment/goodput plus
    # per-tenant attainment — a tenant whose SLO slipped between rounds
    # names itself even when the fleet number holds (mix shift)
    card = data.get("loadtest") or {}
    if card:
        fleet = (card.get("fleet") or {})
        for field in ("slo_attainment_frac", "goodput_tokens_per_s",
                      "goodput_tokens_per_chip_s", "ttft_p99_ms",
                      "itl_p99_ms"):
            if isinstance(fleet.get(field), (int, float)):
                out[f"loadtest/{field}"] = float(fleet[field])
        for name, row in (card.get("tenants") or {}).items():
            for field in ("slo_attainment_frac", "goodput_tokens_per_s"):
                if isinstance(row.get(field), (int, float)):
                    out[f"loadtest/{name}/{field}"] = float(row[field])
        # KV-tiering restore rows (only present when the joined server
        # records saw restores): a restore-latency regression between
        # rounds names the tier plumbing, not the model
        for field in ("kv_restores", "kv_restore_ms_p50"):
            if isinstance(card.get(field), (int, float)):
                out[f"loadtest/{field}"] = float(card[field])
    # incident totals diff like any metric: a round with more incidents
    # (or ones that stay open longer) regressed operationally even when
    # every latency percentile held
    inc = data.get("incidents") or {}
    if inc:
        out["incident/count"] = float(inc.get("count", 0))
        out["incident/open"] = float(inc.get("open", 0))
        if isinstance(inc.get("mean_duration_s"), (int, float)):
            out["incident/mean_duration_s"] = float(inc["mean_duration_s"])
    out["recompiles_diagnosed"] = float(len(data.get("recompiles") or []))
    audit = data.get("audit") or {}
    if audit:
        # audit findings are a regression signal: the counts diff like any
        # metric, and each active P1 additionally travels as its own
        # fingerprint key so a NEW P1 between two runs is flagged even
        # when the count happens to stay level (one fixed, one introduced)
        summ = audit.get("summary") or {}
        out["audit/findings_total"] = float(summ.get("findings_total", 0))
        out["audit/findings_p1"] = float(summ.get("findings_p1", 0))
        for f in audit.get("findings") or []:
            if f.get("severity") == "P1" and f.get("fingerprint"):
                out[f"audit/p1/{f['fingerprint']}"] = 1.0
    return out


# metrics where ANY drop — not just a past-threshold move — is a
# regression: a canary pass ratio below its baseline means the service
# returned wrong tokens, and correctness has no noise budget
_DROP_SENTINEL_MARKERS = ("canary_pass_ratio", "canary/pass_ratio")


def _is_sentinel_drop(key: str, va: float, vb: float,
                      min_abs: float) -> bool:
    return any(m in key for m in _DROP_SENTINEL_MARKERS) and vb < va - min_abs


def diff_metrics(a: dict, b: dict, threshold: float = 0.1,
                 min_abs: float = 1e-9) -> dict:
    """Shared-metric comparison: relative change per metric, the ones
    past ``threshold`` flagged (sorted, biggest mover first). Sentinel
    metrics (canary pass ratio) flag on any decrease."""
    shared = sorted(set(a) & set(b))
    rows = []
    for key in shared:
        va, vb = a[key], b[key]
        if abs(va - vb) <= min_abs:
            rel = 0.0
        elif abs(va) <= min_abs:
            # moved off zero: no finite relative change exists — flag it
            # as `from_zero` with rel_change None (json.dumps(inf) would
            # emit the non-spec `Infinity` token and break --json consumers)
            rel = None
        else:
            rel = (vb - va) / abs(va)
        rows.append({"metric": key, "a": va, "b": vb,
                     "rel_change": round(rel, 4) if rel is not None else None,
                     "from_zero": rel is None,
                     "sentinel": _is_sentinel_drop(key, va, vb, min_abs)})
    # a P1 audit finding that exists only in B is NEW regression evidence
    # even though unshared keys normally stay out of the flag list (the
    # count metrics can stay level when one P1 is fixed and another lands)
    for key in sorted(set(b) - set(a)):
        if key.startswith("audit/p1/"):
            rows.append({"metric": key, "a": 0.0, "b": b[key],
                         "rel_change": None, "from_zero": True,
                         "sentinel": False})
    flagged = [r for r in rows
               if r["from_zero"] or r["sentinel"]
               or abs(r["rel_change"]) > threshold]
    flagged.sort(key=lambda r: -(float("inf") if (r["from_zero"] or r["sentinel"])
                                 else abs(r["rel_change"])))
    return {
        "shared_metrics": len(shared),
        "only_a": sorted(set(a) - set(b)),
        "only_b": sorted(set(b) - set(a)),
        "threshold": threshold,
        "flagged": flagged,
        "rows": rows,
    }


def format_diff(diff: dict, a_name: str, b_name: str) -> str:
    lines = [f"== accelerate-tpu report --diff: {a_name} vs {b_name} =="]
    lines.append(
        f"{diff['shared_metrics']} shared metrics, threshold "
        f"{100 * diff['threshold']:.0f}% — {len(diff['flagged'])} flagged"
    )
    if diff["flagged"]:
        table = [("metric", "A", "B", "change")]
        for r in diff["flagged"][:40]:
            rel = r["rel_change"]
            change = "from zero" if r["from_zero"] else f"{100 * rel:+.1f}%"
            if r.get("sentinel"):
                change += " (correctness sentinel)"
            table.append((
                r["metric"], f"{r['a']:.4g}", f"{r['b']:.4g}", change,
            ))
        lines.extend(render_table(table))
    else:
        lines.append("  no shared metric moved past the threshold")
    if diff["only_a"] or diff["only_b"]:
        lines.append(
            f"  (unshared: {len(diff['only_a'])} only in A, "
            f"{len(diff['only_b'])} only in B)"
        )
    return "\n".join(lines)


def report_command(args) -> int:
    if args.diff:
        a_path, b_path = args.diff
        a, b = collect_diff_metrics(a_path), collect_diff_metrics(b_path)
        if not a or not b:
            missing = a_path if not a else b_path
            print(f"report --diff: no metrics found under {missing} — "
                  "expected BENCH_r*.json or telemetry artifacts",
                  file=sys.stderr)
            return 1
        diff = diff_metrics(a, b, threshold=args.threshold)
        if args.json:
            print(json.dumps(diff))
        else:
            print(format_diff(diff, a_path, b_path))
        return 1 if (args.fail and diff["flagged"]) else 0
    if not args.target:
        print("report: pass a telemetry dir (or --diff A B)", file=sys.stderr)
        return 1
    data = load_report(args.target)
    if not (data["goodput"] or data["costs"].get("executables")
            or data["recompiles"] or data["first_compiles"] or data["steps"]
            or data["timeline"] or data["usage"] or data["alerts"]
            or data["fleet"] or data["waterfall"] or data["canary"]
            or data["incidents"] or data["audit"] or data["loadtest"]):
        print(f"no telemetry artifacts found under {args.target} — expected "
              "goodput-host*.json / costs-host*.json / forensics-host*.jsonl "
              "/ fleet.json / audit.json (see docs/telemetry.md)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(data))
    else:
        print(format_report(data))
    return 0


def register(subparsers):
    parser = subparsers.add_parser(
        "report",
        help="Explain a telemetry dir: goodput breakdown, per-executable "
             "roofline rows, diagnosed recompiles, timeline/alerts/usage "
             "(--diff A B = regression sentry)",
    )
    parser.add_argument("target", nargs="?", default=None,
                        help="telemetry dir (goodput/costs/forensics/"
                             "timeline/alerts/usage artifacts)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                        help="diff two runs (telemetry dirs, bench dirs, or "
                             "BENCH_r*.json files); flags moved metrics")
    parser.add_argument("--threshold", type=float, default=0.1,
                        help="relative change that flags a metric (default 0.10)")
    parser.add_argument("--fail", action="store_true",
                        help="exit 1 when --diff flags any metric (CI sentry)")
    parser.set_defaults(func=report_command)
    return parser
