"""`accelerate-tpu report` — the doctor's read of a telemetry dir.

`trace` answers "show me the timeline"; `report` answers "where did the
time go and why". It merges everything a session leaves behind —

    goodput-host<i>.json     wall-clock partition (the goodput ledger)
    costs-host<i>.json       per-executable roofline rows (cost registry)
    forensics-host<i>.jsonl  diagnosed recompiles with their causes
    metrics-host<i>.jsonl    per-step records (optional)
    requests-host<i>.jsonl   serving request log (optional)

— into one explanation:

    accelerate-tpu report runs/exp/telemetry
    accelerate-tpu report runs/exp/telemetry --json

The text form prints the goodput breakdown (fractions sum to 1.0), the
top executables by measured wall with their roofline class and cost-model
MFU / bandwidth utilization, and every recompile with the exact argument
and aval change that caused it. Pure stdlib + the telemetry host modules:
no jax import, so it runs anywhere the artifacts land.
"""

from __future__ import annotations

import glob
import json
import os
import sys

BAR_WIDTH = 24


def _host_files(target: str, pattern: str) -> list:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, pattern)))
    return []


def _host_of(path: str, prefix: str) -> str:
    name = os.path.basename(path)
    stem = name.split(".", 1)[0]
    return stem[len(prefix):] if stem.startswith(prefix) else "?"


def _load_json(path: str):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _load_jsonl(path: str) -> list:
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    except (OSError, ValueError):
        pass
    return out


def load_goodput(target: str) -> dict:
    """Merged goodput: per-host snapshots plus an aggregate over summed
    bucket seconds (an idle host dilutes fleet goodput — that is the
    point of fleet accounting)."""
    from ..telemetry.goodput import BUCKETS

    hosts = {}
    for path in _host_files(target, "goodput-host*.json"):
        data = _load_json(path)
        if data:
            hosts[_host_of(path, "goodput-host")] = data
    if not hosts:
        return {}
    seconds = {b: 0.0 for b in BUCKETS}
    elapsed = 0.0
    for data in hosts.values():
        elapsed += data.get("elapsed_s") or 0.0
        for b in BUCKETS:
            seconds[b] += (data.get("seconds") or {}).get(b) or 0.0
    total = sum(seconds.values())
    fractions = {b: (seconds[b] / total if total > 0 else 0.0) for b in BUCKETS}
    return {"hosts": hosts, "seconds": seconds, "fractions": fractions,
            "elapsed_s": elapsed}


def load_costs(target: str) -> dict:
    """Merged cost registry: rows keyed by executable name, wall/calls
    summed across hosts, static cost fields from the first host that
    captured them."""
    merged: dict = {}
    peaks = {}
    for path in _host_files(target, "costs-host*.json"):
        data = _load_json(path)
        if not data:
            continue
        for key in ("peak_flops", "peak_hbm_bw", "ridge_intensity"):
            if data.get(key) and key not in peaks:
                peaks[key] = data[key]
        for row in data.get("executables") or []:
            name = row.get("name")
            if name is None:
                continue
            cur = merged.get(name)
            if cur is None:
                merged[name] = dict(row)
            else:
                cur["wall_s"] = round(cur.get("wall_s", 0.0) + (row.get("wall_s") or 0.0), 4)
                cur["calls"] = cur.get("calls", 0) + (row.get("calls") or 0)
                # dynamic rows (per-call cost varies with runtime state —
                # the paged decode kernel) merge by TOTALS, not by the
                # first host's per-call average
                for key in ("flops_total", "hbm_bytes_total"):
                    if row.get(key) is not None or cur.get(key) is not None:
                        cur[key] = (cur.get(key) or 0.0) + (row.get(key) or 0.0)
                for k, v in row.items():
                    cur.setdefault(k, v)
    rows = sorted(merged.values(), key=lambda r: -(r.get("wall_s") or 0.0))
    # re-derive the utilization numbers over the merged wall
    pf, pb = peaks.get("peak_flops"), peaks.get("peak_hbm_bw")
    for row in rows:
        if row.get("dynamic") and row.get("calls"):
            for total, per_call in (("flops_total", "flops_per_call"),
                                    ("hbm_bytes_total", "hbm_bytes_per_call")):
                if row.get(total) is not None:
                    row[per_call] = row[total] / row["calls"]
            # AI / roofline class must come from the merged totals too, or
            # the row would pair fleet-total throughput numbers with host
            # 0's classification
            if row.get("flops_total") and row.get("hbm_bytes_total"):
                ai = row["flops_total"] / row["hbm_bytes_total"]
                row["arith_intensity"] = round(ai, 4)
                ridge = row.get("ridge_intensity") or peaks.get("ridge_intensity")
                if ridge:
                    row["roofline"] = (
                        "compute-bound" if ai >= ridge else "memory-bound"
                    )
        wall, calls = row.get("wall_s") or 0.0, row.get("calls") or 0
        if wall > 0 and calls > 0:
            if row.get("flops_per_call") and pf:
                row["mfu_model_pct"] = round(
                    100.0 * row["flops_per_call"] * calls / wall / pf, 3)
            if row.get("hbm_bytes_per_call"):
                row["hbm_gbps"] = round(
                    row["hbm_bytes_per_call"] * calls / wall / 1e9, 3)
                if pb:
                    row["bw_util_pct"] = round(
                        100.0 * row["hbm_bytes_per_call"] * calls / wall / pb, 3)
    return {**peaks, "executables": rows}


def load_forensics(target: str) -> list:
    """Every forensics record (host-tagged, oldest first)."""
    out = []
    for path in _host_files(target, "forensics-host*.jsonl"):
        host = _host_of(path, "forensics-host")
        for rec in _load_jsonl(path):
            rec.setdefault("host", host)
            out.append(rec)
    out.sort(key=lambda r: r.get("time_unix_s", 0))
    return out


def load_steps(target: str) -> dict:
    """Aggregate of the per-step metrics JSONL (when the run wrote one)."""
    walls, tokens, compiles = [], 0, 0
    for path in _host_files(target, "metrics-host*.jsonl"):
        for rec in _load_jsonl(path):
            if rec.get("wall_s"):
                walls.append(float(rec["wall_s"]) / max(int(rec.get("steps", 1)), 1))
            tokens += rec.get("tokens") or 0
            compiles += rec.get("compile_events") or 0
    if not walls:
        return {}
    walls.sort()
    return {
        "steps": len(walls),
        "step_time_p50_s": round(walls[len(walls) // 2], 4),
        "step_time_max_s": round(walls[-1], 4),
        "tokens": tokens,
        "compile_events": compiles,
    }


def load_report(target: str) -> dict:
    forensics = load_forensics(target)
    data = {
        "target": target,
        "goodput": load_goodput(target),
        "costs": load_costs(target),
        "recompiles": [r for r in forensics if r.get("event") == "recompile"],
        "first_compiles": [r for r in forensics
                           if r.get("event") == "first_compile"],
        "steps": load_steps(target),
    }
    req_files = _host_files(target, "requests-host*.jsonl")
    if req_files:
        from .trace import load_requests, summarize_requests

        data["requests"] = summarize_requests(load_requests(target))
    return data


def _bar(frac: float) -> str:
    n = int(round(max(0.0, min(frac, 1.0)) * BAR_WIDTH))
    return "#" * n + "." * (BAR_WIDTH - n)


def format_report(data: dict) -> str:
    lines = [f"== accelerate-tpu report: {data.get('target', '?')} =="]

    gp = data.get("goodput") or {}
    if gp:
        fr = gp["fractions"]
        lines.append("")
        lines.append(
            f"goodput breakdown ({len(gp.get('hosts') or {})} host(s), "
            f"{gp.get('elapsed_s', 0):.1f}s wall; fractions sum to "
            f"{sum(fr.values()):.2f}):"
        )
        order = ("compute", "compile", "checkpoint", "data_wait", "stall", "idle")
        for b in order:
            f = fr.get(b, 0.0)
            secs = (gp.get("seconds") or {}).get(b, 0.0)
            lines.append(f"  {b:<10} {100 * f:6.1f}%  {_bar(f)}  {secs:9.2f}s")
        lines.append(f"  goodput (productive compute) = {100 * fr.get('compute', 0.0):.1f}%")
    else:
        lines.append("")
        lines.append("goodput breakdown: no goodput-host*.json found "
                     "(run with telemetry enabled)")

    costs = data.get("costs") or {}
    rows = costs.get("executables") or []
    lines.append("")
    if rows:
        ridge = costs.get("ridge_intensity")
        ridge_txt = f"{ridge:.1f}" if isinstance(ridge, (int, float)) else "?"
        lines.append("top executables by measured wall (roofline vs "
                     f"ridge {ridge_txt} flops/byte):")
        header = ("executable", "wall_s", "calls", "class", "AI",
                  "MFU(model)", "BW util", "GB/s")
        table = [header]
        for row in rows[:10]:
            mfu = row.get("mfu_model_pct")
            bw = row.get("bw_util_pct")
            gbps = row.get("hbm_gbps")
            table.append((
                str(row.get("name")),
                f"{row.get('wall_s', 0.0):.3f}" if row.get("wall_s") is not None else "",
                str(row.get("calls", "")),
                row.get("roofline", "?"),
                f"{row['arith_intensity']:.2f}" if row.get("arith_intensity") is not None else "",
                f"{mfu:.2f}%" if mfu is not None else "",
                f"{bw:.2f}%" if bw is not None else "",
                f"{gbps:.1f}" if gbps is not None else "",
            ))
        widths = [max(len(r[i]) for r in table) for i in range(len(header))]
        for r in table:
            lines.append("  " + "  ".join(c.ljust(w) for c, w in zip(r, widths)))
    else:
        lines.append("executables: no costs-host*.json found")

    recs = data.get("recompiles") or []
    firsts = data.get("first_compiles") or []
    lines.append("")
    lines.append(f"recompiles ({len(recs)} diagnosed, "
                 f"{len(firsts)} first compiles):")
    for rec in recs:
        t = rec.get("time_unix_s")
        comp = rec.get("compile_s")
        hits = rec.get("compile_cache_hits") or 0
        suffix = []
        if comp is not None:
            suffix.append(f"compile {comp:.2f}s")
        suffix.append(f"{rec.get('compile_events', '?')} events")
        if hits:
            suffix.append(f"{hits} cache hits")
        stamp = f"[host {rec.get('host', '?')}" + (
            f" @{t:.0f}] " if isinstance(t, (int, float)) else "] ")
        lines.append(f"  {stamp}{rec.get('cause')}  ({', '.join(suffix)})")
    if not recs:
        lines.append("  none — every entry point held its steady-state signature")

    steps = data.get("steps") or {}
    if steps:
        lines.append("")
        lines.append(
            f"steps: {steps['steps']} recorded, p50 {steps['step_time_p50_s']}s, "
            f"max {steps['step_time_max_s']}s, {steps['tokens']} tokens, "
            f"{steps['compile_events']} compile events"
        )
    req = data.get("requests") or {}
    if req.get("requests"):
        lines.append(
            f"serving: {req.get('requests')} requests, {req.get('tokens')} tokens"
            + (f", ttft p50/p99 = {req.get('ttft_p50_ms')}/{req.get('ttft_p99_ms')} ms"
               if req.get("ttft_p50_ms") is not None else "")
        )
    return "\n".join(lines)


def report_command(args) -> int:
    data = load_report(args.target)
    if not (data["goodput"] or data["costs"].get("executables")
            or data["recompiles"] or data["first_compiles"] or data["steps"]):
        print(f"no telemetry artifacts found under {args.target} — expected "
              "goodput-host*.json / costs-host*.json / forensics-host*.jsonl "
              "(see docs/telemetry.md)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(data))
    else:
        print(format_report(data))
    return 0


def register(subparsers):
    parser = subparsers.add_parser(
        "report",
        help="Explain a telemetry dir: goodput breakdown, per-executable "
             "roofline rows, diagnosed recompiles",
    )
    parser.add_argument("target", help="telemetry dir (goodput/costs/forensics artifacts)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.set_defaults(func=report_command)
    return parser
