"""`accelerate-tpu` CLI entry (parity: reference commands/accelerate_cli.py).

Subcommands are registered lazily; each lives in its own module. When the
requested command is recognizable from argv, ONLY that module is imported
— `launch` statically reaches jax (utils/__init__ -> utils.memory), and
the log-reading commands (`trace`, `report`, `watch`, `audit --host-only`)
must run on machines with no accelerator stack and must not bill a jax
import to their startup. A bare `accelerate-tpu` / `--help` imports
everything to render the full command list.
"""

from __future__ import annotations

import argparse
import sys

_COMMANDS = (
    "config", "launch", "estimate", "merge", "test", "tpu_config",
    "trace", "report", "watch", "audit", "serve", "loadtest", "autoscale",
    "incident",
)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        "accelerate-tpu", usage="accelerate-tpu <command> [<args>]"
    )
    subparsers = parser.add_subparsers(dest="command")

    from . import env

    env.register(subparsers)
    requested = next((a for a in argv if not a.startswith("-")), None)
    names = (requested,) if requested in _COMMANDS else _COMMANDS
    for name in names:
        try:
            module = __import__(f"accelerate_tpu.commands.{name}", fromlist=["register"])
            module.register(subparsers)
        except ImportError:
            continue

    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 1
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main() or 0)
