"""`accelerate-tpu` CLI entry (parity: reference commands/accelerate_cli.py).

Subcommands are registered lazily; each lives in its own module. This is a
stub while the CLI layer is built out — `env` works today.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        "accelerate-tpu", usage="accelerate-tpu <command> [<args>]"
    )
    subparsers = parser.add_subparsers(dest="command")

    from . import env

    env.register(subparsers)
    registered = {"env"}
    for name in ("config", "launch", "estimate", "merge", "test", "tpu_config", "trace", "report", "watch"):
        try:
            module = __import__(f"accelerate_tpu.commands.{name}", fromlist=["register"])
            module.register(subparsers)
            registered.add(name)
        except ImportError:
            continue

    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 1
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main() or 0)
