"""`accelerate-tpu test` — run the bundled sanity suite under launch
(parity: reference commands/test.py:65)."""

from __future__ import annotations

import os


def register(subparsers):
    parser = subparsers.add_parser("test", help="Run the bundled distributed sanity checks")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--cpu", action="store_true")
    parser.set_defaults(func=test_command)
    return parser


def test_command(args) -> int:
    import accelerate_tpu.test_utils.scripts.test_script as ts

    script = os.path.abspath(ts.__file__)
    from .accelerate_cli import main as cli_main

    argv = ["launch"]
    if args.config_file:
        argv += ["--config_file", args.config_file]
    if args.num_processes:
        argv += ["--num_processes", str(args.num_processes)]
    if args.cpu:
        argv += ["--cpu"]
    argv += [script]
    code = cli_main(argv)
    if code == 0:
        print("Test is a success! You are ready for your distributed training!")
    return code
