"""`accelerate-tpu env` — platform dump for bug reports (parity: reference
commands/env.py, 113 LoC)."""

from __future__ import annotations

import os


def register(subparsers):
    parser = subparsers.add_parser("env", help="Print environment information")
    parser.add_argument("--config_file", default=None, help="Config file to inspect")
    parser.set_defaults(func=env_command)
    return parser


def env_command(args):
    import accelerate_tpu
    from ..utils.environment import get_platform_info

    info = {"`accelerate_tpu` version": accelerate_tpu.__version__}
    info.update(get_platform_info())
    config_file = getattr(args, "config_file", None)
    if config_file is None:
        from .config_args import default_config_file

        config_file = default_config_file()
    if config_file and os.path.isfile(config_file):
        with open(config_file) as f:
            info["Config"] = f.read().strip()

    print("\nCopy-and-paste the text below in your GitHub issue\n")
    print("\n".join(f"- {k}: {v}" for k, v in info.items()))
    return 0
