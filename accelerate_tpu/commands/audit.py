"""`accelerate-tpu audit` — static invariant checks, before anything runs.

Two passes behind one findings model (``accelerate_tpu.analysis``):

- **host lint** (always; fully jax-free): AST checks over the telemetry/
  serving host modules — lock-order inversions, user callbacks invoked
  under a lock, env-var default traps — plus the import-hygiene
  reachability check against the declared jax-free module set
  (``analysis/hygiene.py``, the same source of truth
  ``tests/test_imports.py`` derives its probes from).
- **program audit** (when jax is importable; ``--host-only`` skips):
  traces the repo's own registered entry points — the paged serving
  engine's full warmup program set and the fused train step — and flags
  baked constants, donation misses, f32 drift, host callbacks and
  weak-shape dependencies. Tracing only: nothing executes, nothing
  compiles (``--compile-check`` opts into the memory_analysis aliasing
  cross-check, which does compile).

Findings carry stable fingerprints; ``audit-baseline.json`` suppresses
the deliberate ones, each with a justification this CLI renders. Exit
status is non-zero exactly when an **unbaselined P1** finding exists, so
the tier-1 test gate doubles as the CI gate.

    accelerate-tpu audit                         # both passes, repo baseline
    accelerate-tpu audit --host-only             # log-only machines: no jax
    accelerate-tpu audit --json                  # machine-readable
    accelerate-tpu audit --out runs/x/telemetry  # audit.json for `report`
    accelerate-tpu audit --update-baseline --justify "why"   # suppress actives
"""

from __future__ import annotations

import json
import os
import sys
import time


def _host_findings(paths, root):
    from ..analysis.host_lint import lint_paths
    from ..analysis.hygiene import hygiene_findings

    findings = lint_paths(paths or None, root=root)
    findings.extend(hygiene_findings(root))
    return findings


def _program_findings(args):
    from ..analysis import program_audit

    kw = {}
    if args.const_mb is not None:
        kw["const_bytes"] = int(args.const_mb * (1 << 20))
    if args.donation_kb is not None:
        kw["donation_bytes"] = int(args.donation_kb * (1 << 10))
    return program_audit.self_audit(
        include_train=not args.no_train, warmup=args.warmup,
        compile_check=args.compile_check, **kw,
    )


def run_audit(args) -> int:
    from ..analysis.findings import (
        Baseline,
        render_findings,
        sort_findings,
        summarize,
    )

    root = args.root or _default_root()
    baseline_path = args.baseline or os.path.join(root, "audit-baseline.json")
    baseline = Baseline.load(baseline_path)

    findings = []
    notes = []
    t0 = time.perf_counter()
    if not args.programs_only:
        findings.extend(_host_findings(args.paths, root))
        notes.append(f"host lint: {time.perf_counter() - t0:.2f}s")
    if not args.host_only:
        try:
            import jax  # noqa: F401  (the program pass needs a backend)

            has_jax = True
        except Exception:
            has_jax = False
        if has_jax:
            t1 = time.perf_counter()
            findings.extend(_program_findings(args))
            notes.append(f"program audit: {time.perf_counter() - t1:.2f}s")
        else:
            notes.append(
                "program audit skipped: jax not importable here (host lint "
                "is authoritative on log-only machines; run the program "
                "pass where the accelerator stack lives)"
            )

    active, suppressed = baseline.split(findings)
    active, suppressed = sort_findings(active), sort_findings(suppressed)
    stale = baseline.stale_entries(findings)

    if args.update_baseline:
        if not args.justify:
            print("audit --update-baseline requires --justify \"<reason>\"",
                  file=sys.stderr)
            return 2
        for f in active:
            baseline.add(f, args.justify)
        baseline.save(baseline_path)
        print(f"baselined {len(active)} finding(s) into {baseline_path}",
              file=sys.stderr)
        suppressed = suppressed + active
        active = []

    payload = {
        "findings": [f.to_dict() for f in active],
        "suppressed": [f.to_dict() for f in suppressed],
        "summary": summarize(active),
        "stale_baseline": stale,
        "baseline": baseline_path if baseline.entries else None,
        "notes": notes,
        "time_unix_s": round(time.time(), 3),
    }
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        out_path = os.path.join(args.out, "audit.json")
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1)
        os.replace(tmp, out_path)

    p1 = payload["summary"]["findings_p1"]
    if args.json:
        print(json.dumps(payload))
        return 1 if p1 else 0

    print(f"== accelerate-tpu audit: {root} ==")
    for note in notes:
        print(f"  ({note})")
    for line in render_findings(active, suppressed):
        print(line)
    if stale:
        print(f"  {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (violation fixed — "
              "delete from the baseline):")
        for fp, entry in sorted(stale.items()):
            print(f"    {fp}  {entry.get('check')}  {entry.get('target')}")
    if p1:
        print(f"audit: {p1} unbaselined P1 finding(s) — failing", file=sys.stderr)
        return 1
    return 0


def _default_root() -> str:
    # the analysis package knows where the repo root is relative to the
    # installed package; a checked-out tree and an installed wheel agree
    from ..analysis.hygiene import repo_root

    return repo_root()


def register(subparsers):
    parser = subparsers.add_parser(
        "audit",
        help="Static invariant audit: lint host code (locks/callbacks/env "
             "defaults, jax-free) and trace registered jitted programs "
             "(baked constants, donation misses, f32 drift); exits non-zero "
             "on unbaselined P1 findings",
    )
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--paths", nargs="*", default=None,
                        help="host-lint paths relative to the root "
                             "(default: telemetry/serving/commands/utils/runtime)")
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--host-only", action="store_true",
                      help="host lint + hygiene only (no jax import — safe "
                           "on log-only machines)")
    mode.add_argument("--programs-only", action="store_true",
                      help="program audit only")
    parser.add_argument("--baseline", default=None,
                        help="suppression file (default: <root>/audit-baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="add every active finding to the baseline "
                             "(requires --justify)")
    parser.add_argument("--justify", default=None,
                        help="justification recorded with --update-baseline")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--out", default=None, metavar="DIR",
                        help="also write audit.json into DIR (what "
                             "`accelerate-tpu report` renders and --diff "
                             "counts as a regression signal)")
    parser.add_argument("--warmup", action="store_true",
                        help="warm the self-audit engine first (compiles; "
                             "audits the post-warmup program set exactly)")
    parser.add_argument("--no-train", action="store_true",
                        help="skip the train-step spec in the program pass")
    parser.add_argument("--compile-check", action="store_true",
                        help="allow .compile() for the memory_analysis "
                             "aliasing cross-check on donation findings")
    parser.add_argument("--const-mb", type=float, default=None,
                        help="baked-constant threshold in MiB (default 1.0)")
    parser.add_argument("--donation-kb", type=float, default=None,
                        help="donation-miss threshold in KiB (default 64)")
    parser.set_defaults(func=run_audit)
    return parser
