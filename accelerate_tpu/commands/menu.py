"""Interactive cursor-selection menu for the config questionnaire.

Parity target: the reference's ``commands/menu/`` package (BulletMenu —
arrow-key selection with a highlighted cursor, reference
``commands/menu/selection_menu.py`` + ``utils/rich.py``). Pure stdlib:
raw-mode termios + ANSI redraw, no rich/curses dependency. When stdin is
not a TTY (CI, piped input) it degrades to a numbered prompt, so scripted
``yes ''``-style flows keep working.
"""

from __future__ import annotations

import os
import sys

_UP = ("\x1b[A", "k")
_DOWN = ("\x1b[B", "j")


def _read_key(fd: int) -> str:
    """One keystroke from the raw fd. os.read, not the buffered stream:
    select() peeks the FD, and buffered readers would already have drained
    the escape sequence's continuation bytes into Python's buffer."""
    import select

    raw = os.read(fd, 1)
    if not raw:  # EOF/hangup: some ptys return b"" instead of raising EIO
        raise EOFError("tty input closed")
    ch = raw.decode(errors="replace")
    if ch == "\x1b":
        # Only consume continuation bytes that are ALREADY pending: a lone
        # ESC press must not swallow the user's next keystroke (or block).
        if not select.select([fd], [], [], 0.05)[0]:
            return ch
        nxt = os.read(fd, 1).decode(errors="replace")
        if nxt == "[":
            return "\x1b[" + os.read(fd, 1).decode(errors="replace")
        return ch + nxt
    return ch


class BulletMenu:
    """``BulletMenu("Mixed precision", ["no", "fp16", "bf16"]).run(default)``
    returns the selected INDEX."""

    def __init__(self, prompt: str, choices):
        self.prompt = prompt
        self.choices = [str(c) for c in choices]

    # -- rendering -----------------------------------------------------
    def _draw(self, pos: int, first: bool, out) -> None:
        if not first:
            out.write(f"\x1b[{len(self.choices)}A")  # cursor up N lines
        for i, choice in enumerate(self.choices):
            marker = "➤ " if i == pos else "  "
            style = ("\x1b[7m", "\x1b[0m") if i == pos else ("", "")
            out.write(f"\r\x1b[2K{marker}{style[0]}{choice}{style[1]}\n")
        out.flush()

    # -- drivers -------------------------------------------------------
    def _run_tty(self, default: int) -> int:
        import termios
        import tty

        out = sys.stdout
        out.write(f"{self.prompt} (↑/↓ + enter):\n")
        pos = default
        self._draw(pos, True, out)
        fd = sys.stdin.fileno()
        old = termios.tcgetattr(fd)
        try:
            # TCSADRAIN, not the default TCSAFLUSH: keystrokes typed (or
            # piped by a test harness) before the menu finished starting
            # must not be discarded
            tty.setcbreak(fd, termios.TCSADRAIN)
            while True:
                key = _read_key(fd)
                if key in _UP:
                    pos = (pos - 1) % len(self.choices)
                elif key in _DOWN:
                    pos = (pos + 1) % len(self.choices)
                elif key.isdigit() and int(key) < len(self.choices):
                    pos = int(key)
                elif key in ("\r", "\n"):
                    return pos
                elif key in ("\x03", "\x1b"):  # ctrl-c / lone esc
                    raise KeyboardInterrupt
                self._draw(pos, False, out)
        finally:
            termios.tcsetattr(fd, termios.TCSADRAIN, old)

    def _run_plain(self, default: int) -> int:
        print(self.prompt)
        for i, choice in enumerate(self.choices):
            marker = "*" if i == default else " "
            print(f"  {marker}[{i}] {choice}")
        try:
            raw = input(f"Selection (default {default}): ").strip()
        except EOFError:
            # closed/hung-up stdin: take the default rather than crashing
            print()
            return default
        if not raw:
            return default
        try:
            idx = int(raw)
        except ValueError:
            # accept the choice text itself
            if raw in self.choices:
                return self.choices.index(raw)
            print(f"  -> {raw!r} not in {self.choices}, keeping {self.choices[default]!r}")
            return default
        if 0 <= idx < len(self.choices):
            return idx
        print(f"  -> {idx} out of range, keeping {self.choices[default]!r}")
        return default

    def run(self, default: int = 0) -> int:
        if sys.stdin.isatty() and sys.stdout.isatty():
            try:
                import termios as _termios

                tty_errors = (ImportError, OSError, EOFError, _termios.error)
            except ImportError:  # pragma: no cover - non-unix
                tty_errors = (ImportError, OSError, EOFError)
            try:
                return self._run_tty(default)
            except tty_errors:  # pragma: no cover - exotic/hung-up ttys
                pass
        return self._run_plain(default)


def choose(prompt: str, choices, default):
    """Menu-select a VALUE from ``choices`` with ``default`` preselected."""
    choices = list(choices)
    idx = choices.index(default) if default in choices else 0
    return choices[BulletMenu(prompt, choices).run(idx)]
