"""``accelerate-tpu loadtest`` — replayable load generation + SLO scorecard.

Three verbs over one workload-spec JSON (docs/serving.md "Load testing
& the SLO scorecard"):

- ``loadtest run SPEC.json`` replays the spec's deterministic schedule
  against a target and prints the scorecard (text or ``--json``). The
  target is ``--url http://host:port`` (a live ReplicaServer or
  RouterServer — **jax-free end to end**, the load box needs no
  accelerator stack) or the default ``--demo`` tiny in-process engine
  (jax pays lazily, the CI/bring-up path).
- ``loadtest replay RESULT`` re-runs the spec embedded in a previous
  run's ``loadtest-offered.json`` and verifies the schedule digest
  matches — the determinism witness as a command.
- ``loadtest sweep SPEC.json --rates 8,16,32`` steps the open-loop
  arrival rate against a fresh demo engine per step and prints the
  throughput-vs-p99 table with the saturation knee marked.

``--out DIR`` writes ``loadtest-offered.json`` + ``loadtest-scorecard.json``
into DIR, where ``accelerate-tpu report DIR`` picks the scorecard up as
its own section and ``report --diff`` grades attainment regressions.
"""

from __future__ import annotations

import dataclasses
import json


def register(subparsers):
    parser = subparsers.add_parser(
        "loadtest",
        help="deterministic load generator + SLO scorecard "
             "(run / replay / sweep)",
    )
    sub = parser.add_subparsers(dest="verb")

    def _common(p, spec_help):
        p.add_argument("spec", help=spec_help)
        p.add_argument("--url", default=None,
                       help="target a live ReplicaServer/RouterServer "
                            "base URL (jax-free); default: in-process "
                            "demo engine")
        p.add_argument("--out", default=None, metavar="DIR",
                       help="write loadtest-offered.json + "
                            "loadtest-scorecard.json here (report-able)")
        p.add_argument("--json", action="store_true")
        p.add_argument("--ttft-slo-ms", type=float, default=None)
        p.add_argument("--itl-slo-ms", type=float, default=None)
        p.add_argument("--chips", type=int, default=1,
                       help="chip count for goodput tokens/s-per-chip")
        p.add_argument("--time-scale", type=float, default=1.0,
                       help="stretch (>1) or compress (<1, 0 = as fast "
                            "as possible) the arrival schedule")
        p.add_argument("--timeout", type=float, default=120.0, metavar="S")
        p.add_argument("--no-instrument", action="store_true",
                       help="outcomes only, no per-token timing (the "
                            "zero-overhead witness baseline)")
        p.add_argument("--seed", type=int, default=None,
                       help="override the spec's seed")

    run = sub.add_parser("run", help="replay a workload spec, grade it")
    _common(run, "workload-spec JSON path")

    replay = sub.add_parser(
        "replay", help="re-run a previous result's embedded spec and "
                       "verify the schedule digest matches"
    )
    _common(replay, "previous loadtest-offered.json (or its dir)")

    sweep = sub.add_parser(
        "sweep", help="step the open-loop arrival rate, emit the "
                      "throughput-vs-p99 knee"
    )
    _common(sweep, "workload-spec JSON path")
    sweep.add_argument("--rates", default="4,8,16,32",
                       help="comma-separated arrival rates (requests/s)")

    parser.set_defaults(func=loadtest_command)


def _demo_engine():
    """Tiny in-process demo engine (lazy jax — the serve CLI's builder,
    shrunk for load drills: paged arena + a small prefix cache so the
    ghost gauges have evictions to simulate)."""
    import argparse as _ap

    from .serve import build_replica_engine

    args = _ap.Namespace(
        config="tiny", max_seq_len=256, init_seed=0, num_slots=4,
        max_cache_len=160, prefill_chunks="16,64", page_size=16,
        temperature=0.0, top_k=None, steps_per_call=1,
        kv_cache_dtype=None, name="loadtest",
    )
    engine = build_replica_engine(args)
    engine.warmup()
    engine.mark_steady()
    return engine


def _spec_from_args(args):
    from ..serving.loadgen import WorkloadSpec

    spec = WorkloadSpec.load(args.spec)
    if args.seed is not None:
        spec = dataclasses.replace(spec, seed=int(args.seed))
    return spec


def _run_once(args, spec, target=None):
    from ..serving import loadgen
    from ..telemetry import scorecard as sc

    target = target if target is not None else (args.url or _demo_engine())
    result = loadgen.run(
        spec, target, instrument=not args.no_instrument,
        time_scale=args.time_scale, timeout_s=args.timeout,
    )
    card = sc.build_scorecard(
        result, ttft_slo_ms=args.ttft_slo_ms, itl_slo_ms=args.itl_slo_ms,
        chips=args.chips, telemetry_dir=args.out,
    )
    if args.out:
        result.write(args.out)
        sc.write_scorecard(args.out, card)
    return result, card


def loadtest_command(args) -> int:
    verb = getattr(args, "verb", None)
    if verb == "run":
        return _cmd_run(args)
    if verb == "replay":
        return _cmd_replay(args)
    if verb == "sweep":
        return _cmd_sweep(args)
    print("usage: accelerate-tpu loadtest {run|replay|sweep} [--help]")
    return 1


def _cmd_run(args) -> int:
    from ..telemetry.scorecard import format_scorecard

    spec = _spec_from_args(args)
    result, card = _run_once(args, spec)
    if args.json:
        print(json.dumps(card, indent=2, sort_keys=True))
    else:
        print("== accelerate-tpu loadtest ==")
        for line in format_scorecard(card):
            print(line)
        print(f"schedule digest: {result.digest}")
    return 0


def _cmd_replay(args) -> int:
    from ..serving.loadgen import WorkloadSpec, load_offered
    from ..telemetry.scorecard import format_scorecard

    prev = load_offered(args.spec)
    if prev is None:
        print(f"no loadtest-offered.json at {args.spec}")
        return 1
    spec = WorkloadSpec.from_json(prev.spec)
    if args.seed is not None:
        spec = dataclasses.replace(spec, seed=int(args.seed))
    result, card = _run_once(args, spec)
    deterministic = result.digest == prev.digest and args.seed is None
    if args.json:
        doc = dict(card)
        doc["replay"] = {
            "previous_digest": prev.digest, "digest": result.digest,
            "schedule_identical": deterministic,
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print("== accelerate-tpu loadtest replay ==")
        for line in format_scorecard(card):
            print(line)
        print(
            f"schedule {'IDENTICAL' if deterministic else 'DIVERGED'}: "
            f"{prev.digest} -> {result.digest}"
        )
    return 0 if deterministic or args.seed is not None else 1


def _cmd_sweep(args) -> int:
    from ..telemetry.scorecard import find_knee, sweep_rows

    spec = _spec_from_args(args)
    rates = [float(r) for r in str(args.rates).split(",") if r.strip()]
    cards = []
    for rate in rates:
        arrival = dict(spec.arrival)
        arrival["rate_rps"] = rate
        stepped = dataclasses.replace(spec, mode="open", arrival=arrival)
        # fresh target per step: saturation at rate k must not poison
        # the queue the k+1 measurement starts from
        _, card = _run_once(args, stepped,
                            target=args.url or _demo_engine())
        cards.append((rate, card))
    rows = sweep_rows(cards)
    knee = find_knee(rows)
    if args.json:
        print(json.dumps({"rows": rows, "knee_index": knee},
                         indent=2, sort_keys=True))
        return 0
    print("== accelerate-tpu loadtest sweep ==")
    print(f"{'rate_rps':>9} {'tok/s':>9} {'ttft_p99_ms':>12} "
          f"{'attainment':>11} {'finished':>9} {'shed':>6}")
    for i, row in enumerate(rows):
        mark = "  <-- knee" if knee == i else ""
        print(f"{row['rate_rps']:>9g} {row['tokens_per_s']:>9} "
              f"{str(row['ttft_p99_ms']):>12} "
              f"{row['slo_attainment_frac']:>11} {row['finished']:>9} "
              f"{row['shed']:>6}{mark}")
    if knee is None:
        print("no saturation knee within the swept rates")
    return 0
