"""``accelerate-tpu autoscale`` — the closed-loop serving front door.

Runs the same jax-free router tier ``serve router`` runs, with the
burn-rate-actuated autoscaler daemon (``serving/autoscaler.py``)
attached: the fleet collector is built with the ITL SLO so the default
``itl_burn_rate``/``shed_burn_rate`` rules evaluate over the merged
timeline, and every firing can become a canary-gated scale-out (and
every sustained surplus a drained scale-in) instead of a page.

    accelerate-tpu autoscale --replica r0=http://127.0.0.1:8900 \\
        --itl-slo-ms 50 --min-replicas 1 --max-replicas 4 \\
        --log-dir runs/serve

Every decision (holds included) appends to ``autoscale-decisions.jsonl``
under ``--log-dir`` with the full signal snapshot that justified it;
``accelerate-tpu report runs/serve`` renders the decision history and
``report --diff`` tracks ``autoscale_reaction_s``. ``--once`` evaluates
a single decision, prints it as JSON, and exits (scripting / drills).

Jax-free end to end (declared in ``analysis/hygiene.py``) — the
jax-paying work happens in the replica subprocesses the daemon spawns
via ``serve replica``.
"""

from __future__ import annotations

import json


def register(subparsers):
    parser = subparsers.add_parser(
        "autoscale",
        help="run the router with the burn-rate-actuated autoscaler "
             "daemon (canary-gated scale-out, drained scale-in)",
    )
    parser.add_argument("--replica", action="append", default=[],
                        metavar="[NAME=]URL",
                        help="initial replica base URL (repeatable); the "
                             "daemon spawns more via 'serve replica'")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8790)
    parser.add_argument("--log-dir", default=None, metavar="DIR",
                        help="write autoscale-decisions.jsonl, the router "
                             "logs and fleet events here")
    parser.add_argument("--poll-interval", type=float, default=0.5,
                        metavar="S", help="fleet scrape cadence")
    parser.add_argument("--interval", type=float, default=1.0, metavar="S",
                        help="autoscaler evaluation cadence")
    parser.add_argument("--itl-slo-ms", type=float, default=None,
                        help="ITL SLO the burn-rate rule spends against "
                             "(unset = shed-rate burn only)")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--max-replicas", type=int, default=4)
    parser.add_argument("--headroom-floor", type=float, default=0.15,
                        help="scale out when burn fires AND fleet headroom "
                             "is below this fraction")
    parser.add_argument("--scale-in-headroom", type=float, default=0.5,
                        help="consider scale-in above this headroom "
                             "fraction (and no burn firing)")
    parser.add_argument("--scale-in-margin", type=float, default=1.25,
                        help="N-1 capacity must clear projected load "
                             "times this margin")
    parser.add_argument("--cooldown", type=float, default=30.0, metavar="S",
                        help="hold after any action while the new "
                             "membership's signals settle")
    parser.add_argument("--confirm-evals", type=int, default=2,
                        help="consecutive eligible evaluations before "
                             "acting (flap suppression)")
    parser.add_argument("--fast-window", type=float, default=60.0,
                        metavar="S")
    parser.add_argument("--slow-window", type=float, default=600.0,
                        metavar="S")
    parser.add_argument("--horizon", type=float, default=60.0, metavar="S",
                        help="forecast horizon for the projected load")
    parser.add_argument("--replica-arg", action="append", default=[],
                        metavar="ARG",
                        help="extra 'serve replica' CLI argument for "
                             "spawned replicas (repeatable, e.g. "
                             "--replica-arg=--num-slots "
                             "--replica-arg=8)")
    parser.add_argument("--startup-timeout", type=float, default=120.0,
                        metavar="S", help="spawn-to-handshake deadline")
    parser.add_argument("--canary-prompt", default="1,2,3",
                        help="comma-separated golden prompt token ids for "
                             "the pre-registration readiness gate")
    parser.add_argument("--canary-max-new-tokens", type=int, default=8)
    parser.add_argument("--canary-seed", type=int, default=0)
    parser.add_argument("--canary-probes", type=int, default=2,
                        help="passing probes required before a spawned "
                             "replica may register")
    parser.add_argument("--once", action="store_true",
                        help="evaluate one decision, print it as JSON, "
                             "exit (no actuation daemon)")
    parser.set_defaults(func=autoscale_command)
    return parser


def _policy_from_args(args):
    from ..telemetry.capacity import AutoscalePolicy

    return AutoscalePolicy(
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        headroom_floor=args.headroom_floor,
        scale_in_headroom=args.scale_in_headroom,
        scale_in_margin=args.scale_in_margin,
        cooldown_s=args.cooldown,
        confirm_evals=args.confirm_evals,
        horizon_s=args.horizon,
        fast_s=args.fast_window,
        slow_s=args.slow_window,
    )


def autoscale_command(args) -> int:
    # jax-free by construction: router + fleet + autoscaler only
    from ..serving.autoscaler import Autoscaler, SubprocessSpawner
    from ..serving.router import Router, RouterConfig, RouterServer
    from ..telemetry.fleet import FleetCollector
    from .serve import _parse_replica_flags

    pairs = _parse_replica_flags(args.replica)
    collector = FleetCollector(
        [(n, u.rstrip("/") + "/metrics") for n, u in pairs],
        poll_interval_s=args.poll_interval,
        itl_slo_ms=args.itl_slo_ms,
        log_dir=args.log_dir,
    )
    cfg = RouterConfig(
        poll_interval_s=args.poll_interval,
        log_dir=args.log_dir,
    )
    router = Router(pairs, config=cfg, collector=collector).start()
    prompt = [int(t) for t in str(args.canary_prompt).split(",") if t.strip()]
    goldens = [{"prompt": prompt, "seed": int(args.canary_seed),
                "max_new_tokens": int(args.canary_max_new_tokens)}]
    autoscaler = Autoscaler(
        router,
        policy=_policy_from_args(args),
        spawner=SubprocessSpawner(
            replica_args=tuple(args.replica_arg) or ("--config", "tiny"),
            startup_timeout_s=args.startup_timeout,
        ),
        goldens=goldens,
        canary_probes=args.canary_probes,
        log_dir=args.log_dir,
        interval_s=args.interval,
    )
    router.attach_autoscaler(autoscaler)
    if args.once:
        try:
            collector.poll_once()
            record = autoscaler.evaluate_once()
            print(json.dumps(record, indent=1, sort_keys=True))
        finally:
            router.close()
        return 0
    autoscaler.start()
    server = RouterServer(router, host=args.host, port=args.port)
    print(json.dumps({
        "role": "autoscale", "port": server.port,
        "replicas": len(pairs),
        "min_replicas": args.min_replicas,
        "max_replicas": args.max_replicas,
        "log_dir": args.log_dir,
    }), flush=True)
    try:
        import time

        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        router.close()
    return 0
