"""`accelerate-tpu tpu-config` — run setup/install commands on every worker
of a TPU pod (parity: reference commands/tpu.py `accelerate tpu-config`:
gcloud ssh fan-out with optional `pip install` of the training deps).

`launch` already fans the training job out; this command covers the
one-time environment setup the reference's tpu-config does: installing
packages, syncing code, or arbitrary shell on `--worker=all`.
"""

from __future__ import annotations

import shlex
import subprocess

from .config_args import load_config_from_file


def register(subparsers):
    parser = subparsers.add_parser(
        "tpu-config", help="Run setup commands on every TPU pod worker"
    )
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument("--tpu_project", default=None)
    parser.add_argument(
        "--command", action="append", default=None,
        help="Command to run on all workers (repeatable; joined with '; ')",
    )
    parser.add_argument(
        "--install_accelerate", action="store_true",
        help="pip install this package on every worker first",
    )
    parser.add_argument(
        "--accelerate_version", default="latest",
        help="Version to install with --install_accelerate ('latest' or a pin)",
    )
    parser.add_argument("--use_sudo", action="store_true", help="Run setup commands under sudo")
    parser.add_argument("--debug", action="store_true", help="Print the gcloud command instead of running it")
    parser.set_defaults(func=tpu_config_command)
    return parser


def build_remote_command(args, config) -> list:
    commands = []
    if args.install_accelerate:
        if args.accelerate_version == "latest":
            spec = "accelerate-tpu"
        else:
            spec = f"accelerate-tpu=={args.accelerate_version}"
        commands.append(f"pip install -U {shlex.quote(spec)}")
    commands.extend(args.command or [])
    if not commands:
        raise ValueError("nothing to run: pass --command and/or --install_accelerate")
    if args.use_sudo:
        commands = [f"sudo {c}" for c in commands]
    remote = "; ".join(commands)
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh",
        args.tpu_name or config.tpu_name,
        f"--zone={args.tpu_zone or config.tpu_zone}",
        "--worker=all",
        f"--command={remote}",
    ]
    project = args.tpu_project or getattr(config, "tpu_project", None)
    if project:
        cmd.append(f"--project={project}")
    return cmd


def tpu_config_command(args) -> int:
    config = load_config_from_file(args.config_file)
    if not (args.tpu_name or config.tpu_name):
        print("No TPU name given (--tpu_name or config file)")
        return 1
    cmd = build_remote_command(args, config)
    if args.debug:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    print(f"Running on all workers of {args.tpu_name or config.tpu_name}...")
    return subprocess.run(cmd).returncode
