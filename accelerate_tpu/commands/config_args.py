"""Config file dataclasses + default path (parity: reference
commands/config/config_args.py, 252 LoC: BaseConfig/ClusterConfig to/from yaml).

The config cascade (SURVEY §5 config/flag system): yaml file < env vars <
programmatic objects. This module is the yaml layer.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

DEFAULT_CONFIG_FOLDER = os.environ.get(
    "ACCELERATE_TPU_CONFIG_HOME", os.path.join(os.path.expanduser("~"), ".cache", "accelerate_tpu")
)


def default_config_file() -> str:
    return os.path.join(DEFAULT_CONFIG_FOLDER, "default_config.yaml")


@dataclass
class ClusterConfig:
    """Everything `accelerate-tpu launch` needs to start a run."""

    compute_environment: str = "LOCAL_MACHINE"  # or TPU_POD
    mixed_precision: str = "no"
    num_processes: int = 1  # hosts
    num_devices_per_process: Optional[int] = None
    main_process_ip: Optional[str] = None
    main_process_port: Optional[int] = None
    # sharding
    sharding_strategy: str = "AUTO"
    data_parallel: int = -1
    fsdp: int = 1
    tensor_parallel: int = 1
    sequence_parallel: int = 1
    expert_parallel: int = 1
    pipeline_parallel: int = 1
    replica: int = 1
    # cross-slice gradient all-reduce dtype: bfloat16/float16/int8
    # (bf16/fp16 aliases accepted); validated by ShardingConfig
    grad_compression_dtype: Optional[str] = None
    # pod fan-out
    tpu_name: Optional[str] = None
    tpu_zone: Optional[str] = None
    tpu_project: Optional[str] = None
    # misc
    debug: bool = False
    downcast_bf16: bool = False
    compilation_cache_dir: Optional[str] = None

    def to_dict(self) -> dict:
        result = dataclasses.asdict(self)
        return {k: v for k, v in result.items() if v is not None}

    def to_yaml_file(self, path: str | os.PathLike):
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        try:
            import yaml

            with open(path, "w") as f:
                yaml.safe_dump(self.to_dict(), f)
        except ImportError:
            with open(path, "w") as f:
                json.dump(self.to_dict(), f, indent=2)

    @classmethod
    def from_yaml_file(cls, path: str | os.PathLike) -> "ClusterConfig":
        with open(path) as f:
            raw = f.read()
        try:
            import yaml

            data = yaml.safe_load(raw)
        except ImportError:
            data = json.loads(raw)
        # renamed-key migrations: old spellings carry their value forward
        renames = {"num_machines": "num_processes"}
        for old, new in renames.items():
            if old in data and new not in data:
                data[new] = data.pop(old)
        known = {f.name for f in dataclasses.fields(cls)}
        extra = {k: v for k, v in data.items() if k not in known}
        if extra:
            import logging

            logging.getLogger(__name__).warning(f"ignoring unknown config keys: {sorted(extra)}")
        return cls(**{k: v for k, v in data.items() if k in known})


def load_config_from_file(path: Optional[str] = None) -> ClusterConfig:
    path = path or default_config_file()
    if os.path.isfile(path):
        return ClusterConfig.from_yaml_file(path)
    return ClusterConfig()
