"""`accelerate-tpu merge-weights` — consolidate a sharded checkpoint into
one file (parity: reference commands/merge.py:69 over
torch.distributed.checkpoint; ours reads the sharded-safetensors layout
written by Accelerator.save_state / save_model)."""

from __future__ import annotations

import os


def register(subparsers):
    parser = subparsers.add_parser(
        "merge-weights", help="Merge a sharded checkpoint into a single file"
    )
    parser.add_argument("checkpoint_dir", help="Directory with model shards (save_state output)")
    parser.add_argument("output_path", help="Destination .safetensors file")
    parser.add_argument("--unsafe_serialization", action="store_true", help="Write pickle instead of safetensors")
    parser.set_defaults(func=merge_command)
    return parser


def merge_command(args) -> int:
    from ..utils.serialization import load_flat_dict, save_pytree

    import glob

    src = args.checkpoint_dir
    # accept either the checkpoint dir itself or one containing model.safetensors*
    # or a per-rank distributed checkpoint (model_0.rank*.manifest.json)
    candidates = [src]
    if os.path.isdir(src):
        manifests = sorted(glob.glob(os.path.join(src, "*.rank*.manifest.json")))
        if manifests:
            base = manifests[0].split(".rank")[0]
            candidates.insert(0, base)
        else:
            for stem in ("model.safetensors", "model.safetensors.index.json",
                         "model_0.safetensors", "model.bin"):
                p = os.path.join(src, stem)
                if os.path.exists(p):
                    candidates.insert(0, p)
                    break
    flat = load_flat_dict(candidates[0])
    out = args.output_path
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    save_pytree(flat, out, safe_serialization=not args.unsafe_serialization)
    print(f"merged {len(flat)} tensors from {src} -> {out}")
    return 0
