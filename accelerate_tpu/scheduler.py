"""Scheduler wrapper (parity: /root/reference/src/accelerate/scheduler.py,
98 LoC: AcceleratedScheduler).

In optax the learning-rate schedule is a pure function of the update count
and is evaluated *inside* the fused jit update — there is no stateful
`.step()` to call. This wrapper keeps the reference call-site contract
(``scheduler.step()`` after ``optimizer.step()``, ``get_last_lr``,
``state_dict``) and preserves the semantics that the schedule only advances
when the optimizer really stepped (reference scheduler.py:54-82): the
authoritative counter is the engine's ``step_count``, which accumulation or
fp16-skip never bumps.
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional

from .state import GradientState


class AcceleratedScheduler:
    def __init__(
        self,
        schedule: Callable[[int], float],
        optimizers=None,
        split_batches: bool = False,
        step_with_optimizer: bool = True,
    ):
        # ``schedule`` is an optax schedule fn: step -> lr. It must be the
        # SAME schedule baked into the optax optimizer passed to prepare()
        # (optax evaluates it in the update); this wrapper only reports it.
        self.schedule = schedule
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.split_batches = split_batches
        self.step_with_optimizer = step_with_optimizer
        self.gradient_state = GradientState()
        self._manual_steps = 0
        self._warned_drift = False

    @property
    def _engine(self):
        for opt in self.optimizers:
            if opt is not None and getattr(opt, "engine", None) is not None:
                return opt.engine
        return None

    @property
    def last_step(self) -> int:
        # Detached mode: the manual counter IS the schedule position the
        # user asked for — reporting the engine count here would silently
        # reattach the schedule (VERDICT r1 drift bug).
        if not self.step_with_optimizer:
            return self._manual_steps
        engine = self._engine
        if engine is not None:
            return int(engine.step_count)
        return self._manual_steps

    def step(self, *args, **kwargs):
        """Parity no-op-with-bookkeeping: optax advanced the schedule inside
        the fused update; we only track manual counts for the detached case."""
        if not self.step_with_optimizer:
            self._manual_steps += 1
            engine = self._engine
            if (
                engine is not None
                and engine.schedule is self.schedule
                and int(engine.step_count) != self._manual_steps
                and not self._warned_drift
            ):
                # the schedule object is ALSO baked into the optax chain,
                # where it advances with the engine's real update count —
                # detached manual stepping cannot move that copy
                warnings.warn(
                    "AcceleratedScheduler(step_with_optimizer=False) counts "
                    f"{self._manual_steps} manual steps but the optimizer has "
                    f"applied {int(engine.step_count)} updates with the same "
                    "schedule baked into its optax chain; the learning rate "
                    "used by the optimizer follows the update count. Build "
                    "the optimizer with a constant lr (optax.sgd(lr)) and "
                    "drive the lr purely from this scheduler, or keep "
                    "step_with_optimizer=True.",
                    stacklevel=2,
                )
                self._warned_drift = True
        # when attached, nothing to do: engine.step_count is authoritative
        # and already excludes accumulation/skipped steps.

    def get_last_lr(self):
        return [float(self.schedule(self.last_step))]

    def get_lr(self):
        return self.get_last_lr()

    def state_dict(self):
        return {"manual_steps": self._manual_steps}

    def load_state_dict(self, state_dict):
        self._manual_steps = state_dict.get("manual_steps", 0)
