"""Experiment trackers (parity: /root/reference/src/accelerate/tracking.py,
1,023 LoC: GeneralTracker ABC + 7 built-ins + filter_trackers).

Same plugin design: a `GeneralTracker` ABC whose methods are gated to the
main process, concrete trackers for tensorboard/wandb/mlflow/comet/aim/
clearml/dvclive when their packages are importable, plus a dependency-free
`JSONLTracker` (always available — useful on TPU pods where only the main
host has egress).
"""

from __future__ import annotations

import json
import os
import time
from functools import wraps
from typing import Any, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_tensorboard_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def on_main_process(function):
    """Run tracker methods on the main process only (reference tracking.py:67)."""

    @wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True):
            state = PartialState()
            if state.is_main_process:
                return function(self, *args, **kwargs)
        else:
            return function(self, *args, **kwargs)

    return execute_on_main_process


def get_available_trackers() -> list:
    out = [LoggerType.JSONL]
    if is_tensorboard_available():
        out.append(LoggerType.TENSORBOARD)
    if is_wandb_available():
        out.append(LoggerType.WANDB)
    if is_mlflow_available():
        out.append(LoggerType.MLFLOW)
    if is_comet_ml_available():
        out.append(LoggerType.COMETML)
    if is_aim_available():
        out.append(LoggerType.AIM)
    if is_clearml_available():
        out.append(LoggerType.CLEARML)
    if is_dvclive_available():
        out.append(LoggerType.DVCLIVE)
    return out


class GeneralTracker:
    """Tracker ABC (reference tracking.py:91)."""

    main_process_only = True
    name = "blank"
    requires_logging_directory = False

    def __init__(self, _blank: bool = False):
        self._blank = _blank

    @property
    def tracker(self):
        return None

    def store_init_configuration(self, values: dict):
        pass

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        pass

    def finish(self):
        pass


class JSONLTracker(GeneralTracker):
    """Append-only metrics file, one JSON object per log call."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Union[str, os.PathLike]):
        super().__init__()
        self.run_name = run_name
        from .telemetry.artifacts import ArtifactWriter

        self.path = os.path.join(logging_dir, run_name, "metrics.jsonl")
        self._fh = ArtifactWriter(self.path)

    @property
    def tracker(self):
        return self._fh

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._write({"event": "config", "values": _jsonable(values)})

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self._write({"event": "log", "step": step, "time": time.time(), "values": _jsonable(values)})

    def _write(self, obj):
        self._fh.write_line(json.dumps(obj))

    @on_main_process
    def finish(self):
        self._fh.close()


class TensorBoardTracker(GeneralTracker):
    """reference tracking.py:165 — via torch.utils.tensorboard or tensorboardX."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Union[str, os.PathLike], **kwargs):
        super().__init__()
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard

        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)
        logger.debug(f"Initialized TensorBoard project {self.run_name} logging to {self.logging_dir}")

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(_flatten_scalars(values), metric_dict={})
        self.writer.flush()
        try:
            import yaml

            with open(os.path.join(self.logging_dir, "hparams.yml"), "w") as outfile:
                yaml.dump(_jsonable(values), outfile)
        except Exception:
            with open(os.path.join(self.logging_dir, "hparams.json"), "w") as outfile:
                json.dump(_jsonable(values), outfile)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        values = _jsonable(values)
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """reference tracking.py:276."""

    name = "wandb"
    requires_logging_directory = False
    main_process_only = True

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import wandb

        self.run_name = run_name
        self.run = wandb.init(project=self.run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """reference tracking.py:579."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, experiment_name: Optional[str] = None, logging_dir=None, **kwargs):
        super().__init__()
        import mlflow

        experiment_name = os.environ.get("MLFLOW_EXPERIMENT_NAME", experiment_name)
        mlflow.set_experiment(experiment_name)
        self.active_run = mlflow.start_run(**kwargs)

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for name, value in list(values.items()):
            if len(str(value)) > mlflow.utils.validation.MAX_PARAM_VAL_LENGTH:
                del values[name]
        mlflow.log_params(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        metrics = {k: v for k, v in values.items() if isinstance(v, (int, float))}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """reference tracking.py:399."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from comet_ml import Experiment

        self.run_name = run_name
        self.writer = Experiment(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.log_metric(k, v, step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.log_other(k, v, **kwargs)
            elif isinstance(v, dict):
                self.writer.log_metrics(v, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.end()


class AimTracker(GeneralTracker):
    """reference tracking.py:480."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir=".", **kwargs):
        super().__init__()
        from aim import Run

        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for key, value in values.items():
            self.writer.track(value, name=key, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    """reference tracking.py:724."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, **kwargs):
        super().__init__()
        from clearml import Task

        current = Task.current_task()
        self._initialized_externally = current is not None
        self.task = current or Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)) and step is not None:
                clearml_logger.report_scalar(title=k, series=k, value=v, iteration=step, **kwargs)
            else:
                clearml_logger.report_single_value(name=k, value=v, **kwargs)

    @on_main_process
    def finish(self):
        if self.task and not self._initialized_externally:
            self.task.close()


class DVCLiveTracker(GeneralTracker):
    """reference tracking.py:876."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, live=None, **kwargs):
        super().__init__()
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(_flatten_scalars(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            self.live.log_metric(k, v, **kwargs)

    @on_main_process
    def finish(self):
        self.live.end()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}


def filter_trackers(log_with, logging_dir=None):
    """Resolve "all"/names/instances to available tracker types
    (reference tracking.py:971)."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    loggers = []
    available = get_available_trackers()
    if "all" in log_with or LoggerType.ALL in log_with:
        loggers = [t for t in available]
    else:
        for item in log_with:
            if isinstance(item, GeneralTracker):
                loggers.append(item)
                continue
            try:
                item = LoggerType(str(item))
            except ValueError:
                raise ValueError(
                    f"Unknown tracker {item!r}; choose from {[str(t) for t in available]}"
                )
            if item not in available:
                logger.warning(f"Tried adding logger {item} but package is not installed; skipping.")
            else:
                loggers.append(item)
    for t in loggers:
        if not isinstance(t, GeneralTracker) and LOGGER_TYPE_TO_CLASS[t.value].requires_logging_directory and logging_dir is None:
            raise ValueError(f"Logging with `{t}` requires a `logging_dir` (set project_dir)")
    return loggers


def resolve_trackers(log_with, project_name: str, logging_dir=None, init_kwargs: dict = {}) -> list:
    trackers = []
    for t in log_with:
        if isinstance(t, GeneralTracker):
            trackers.append(t)
            continue
        cls = LOGGER_TYPE_TO_CLASS[t.value]
        kw = init_kwargs.get(t.value, {})
        if cls.requires_logging_directory:
            trackers.append(cls(project_name, logging_dir, **kw))
        else:
            trackers.append(cls(project_name, **kw))
    return trackers


def _jsonable(values):
    import numpy as np

    out = {}
    for k, v in values.items():
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            out[k] = v.item()
        elif isinstance(v, (np.ndarray,)):
            out[k] = v.tolist()
        elif isinstance(v, dict):
            out[k] = _jsonable(v)
        elif hasattr(v, "__array__") and not isinstance(v, (int, float, str, bool)):
            # non-scalar device arrays (telemetry gauges, user extras):
            # pull to host so json.dumps doesn't choke on jax.Array
            out[k] = np.asarray(v).tolist()
        else:
            out[k] = v
    return out


def _flatten_scalars(values, prefix=""):
    flat = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_scalars(v, prefix=key + "/"))
        elif isinstance(v, (int, float, str, bool)):
            flat[key] = v
        else:
            flat[key] = str(v)
    return flat
