"""notebook_launcher / debug_launcher
(parity: reference launchers.py, 302 LoC).

The torch version must xmp.spawn 8 processes on TPU (one per core) or fork
CUDA workers; JAX drives every local chip from ONE process, so
``notebook_launcher`` on a single host is just "call the function" after
setting launch env. Multi-process remains for the CPU/gloo debug path and
multi-host notebooks (each host runs its own kernel).
"""

from __future__ import annotations

import multiprocessing
import os
import subprocess
import sys
import tempfile
import textwrap
import time
from typing import Optional

from .utils.environment import env_var


def notebook_launcher(
    function,
    args=(),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    **kwargs,
):
    """Run ``function(*args)`` under the launch env contract.

    - single host (the TPU case): executes inline — one process already
      sees all chips, nothing to spawn (reference must xmp.spawn instead);
    - ``num_processes > 1``: spawns CPU/gloo workers like debug_launcher
      (reference notebook GPU path).
    """
    if num_processes is None or num_processes <= 1:
        os.environ[env_var("MIXED_PRECISION")] = mixed_precision
        return function(*args)
    return _spawn_and_run(
        function, args, num_processes, mixed_precision, master_addr, use_port
    )


def debug_launcher(function, args=(), num_processes: int = 2):
    """Fork a world of ``num_processes`` CPU workers over gloo-on-localhost
    (reference debug_launcher:269 — world_size=2 CPU fork)."""
    return _spawn_and_run(function, args, num_processes, "no", "127.0.0.1", _free_port())


def _free_port() -> str:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return str(s.getsockname()[1])


def _worker_env(rank, num_processes, mixed_precision, addr, port):
    return {
        "JAX_PLATFORMS": "cpu",
        env_var("MIXED_PRECISION"): mixed_precision,
        env_var("COORDINATOR_ADDRESS"): f"{addr}:{port}",
        env_var("NUM_PROCESSES"): str(num_processes),
        env_var("PROCESS_ID"): str(rank),
        env_var("LOCAL_PROCESS_ID"): str(rank),
        env_var("FORK_LAUNCHED"): "1",
    }


# Env vars that must not leak into workers (TPU-tunnel sitecustomize).
_WORKER_ENV_DROP = ("PALLAS_AXON_POOL_IPS",)


def _fork_worker(function, args, overrides):
    for key in _WORKER_ENV_DROP:
        os.environ.pop(key, None)
    os.environ.update(overrides)
    function(*args)


def _jax_backends_initialized() -> bool:
    mods = sys.modules
    if "jax" not in mods:
        return False
    try:
        import jax._src.xla_bridge as xla_bridge

        return xla_bridge.backends_are_initialized()
    except Exception:
        return True  # unknown jax internals: assume live, take the safe path


def monitor_group(procs, *, poll, terminate, kill, wait, interval=0.05, grace=5.0) -> int:
    """Poll a worker group until all exit 0; on the first non-zero exit,
    terminate the rest (survivors blocked in collectives would hang forever),
    escalating to kill() if a worker ignores SIGTERM for ``grace`` seconds.
    Returns the first non-zero exit code, or 0. Shared by the notebook/debug
    launchers (mp.Process and subprocess workers) and `accelerate-tpu launch`.
    """
    while True:
        codes = [poll(p) for p in procs]
        bad = [c for c in codes if c not in (None, 0)]
        if bad:
            for p, c in zip(procs, codes):
                if c is None:
                    terminate(p)
            deadline = time.monotonic() + grace
            for p in procs:
                if not wait(p, max(0.0, deadline - time.monotonic())):
                    kill(p)
                    wait(p, grace)
            return bad[0]
        if all(c == 0 for c in codes):
            return 0
        time.sleep(interval)


def _mp_group_kwargs():
    return dict(
        poll=lambda p: None if p.is_alive() else p.exitcode,
        terminate=lambda p: p.terminate(),
        kill=lambda p: p.kill(),
        wait=lambda p, t: (p.join(t), not p.is_alive())[1],
    )


def _subprocess_group_kwargs():
    def _wait(p, timeout):
        try:
            p.wait(timeout=timeout)
            return True
        except subprocess.TimeoutExpired:
            return False

    return dict(
        poll=lambda p: p.poll(),
        terminate=lambda p: p.terminate(),
        kill=lambda p: p.kill(),
        wait=_wait,
    )


_WORKER_TEMPLATE = """
import cloudpickle, sys
with open({payload!r}, "rb") as f:
    function, args = cloudpickle.load(f)
function(*args)
"""


def _spawn_and_run(function, args, num_processes, mixed_precision, addr, port):
    """Run ``num_processes`` gloo-on-localhost workers.

    Default path: ``fork`` — children inherit ``__main__``, so functions
    defined in a notebook or a directly-run script work without any pickling
    (reference uses fork-based start_processes for the same reason). If jax
    backends are already initialized in this process, forking would inherit
    live runtime state, so fall back to fresh subprocesses with the function
    serialized by value via cloudpickle (which, unlike pickle, survives
    ``__main__``-defined functions and closures).
    """
    if not _jax_backends_initialized():
        ctx = multiprocessing.get_context("fork")
        procs = []
        for rank in range(num_processes):
            overrides = _worker_env(rank, num_processes, mixed_precision, addr, port)
            p = ctx.Process(target=_fork_worker, args=(function, tuple(args), overrides))
            p.start()
            procs.append(p)
        code = monitor_group(procs, **_mp_group_kwargs())
    else:
        import cloudpickle

        with tempfile.TemporaryDirectory() as td:
            payload = os.path.join(td, "fn.pkl")
            with open(payload, "wb") as f:
                cloudpickle.dump((function, tuple(args)), f)
            script = os.path.join(td, "worker.py")
            with open(script, "w") as f:
                f.write(textwrap.dedent(_WORKER_TEMPLATE).format(payload=payload))
            procs = []
            for rank in range(num_processes):
                env = dict(os.environ)
                for key in _WORKER_ENV_DROP:
                    env.pop(key, None)
                env.update(_worker_env(rank, num_processes, mixed_precision, addr, port))
                procs.append(subprocess.Popen([sys.executable, script], env=env))
            code = monitor_group(procs, **_subprocess_group_kwargs())
    if code:
        raise RuntimeError(f"launcher worker failed with exit code {code}")
