"""notebook_launcher / debug_launcher
(parity: reference launchers.py, 302 LoC).

The torch version must xmp.spawn 8 processes on TPU (one per core) or fork
CUDA workers; JAX drives every local chip from ONE process, so
``notebook_launcher`` on a single host is just "call the function" after
setting launch env. Multi-process remains for the CPU/gloo debug path and
multi-host notebooks (each host runs its own kernel).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import textwrap
from typing import Optional

from .utils.environment import env_var


def notebook_launcher(
    function,
    args=(),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    **kwargs,
):
    """Run ``function(*args)`` under the launch env contract.

    - single host (the TPU case): executes inline — one process already
      sees all chips, nothing to spawn (reference must xmp.spawn instead);
    - ``num_processes > 1``: spawns CPU/gloo workers like debug_launcher
      (reference notebook GPU path).
    """
    if num_processes is None or num_processes <= 1:
        os.environ[env_var("MIXED_PRECISION")] = mixed_precision
        return function(*args)
    return _spawn_and_run(
        function, args, num_processes, mixed_precision, master_addr, use_port
    )


def debug_launcher(function, args=(), num_processes: int = 2):
    """Fork a world of ``num_processes`` CPU workers over gloo-on-localhost
    (reference debug_launcher:269 — world_size=2 CPU fork)."""
    return _spawn_and_run(function, args, num_processes, "no", "127.0.0.1", _free_port())


def _free_port() -> str:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return str(s.getsockname()[1])


_WORKER_TEMPLATE = """
import pickle, sys
with open({payload!r}, "rb") as f:
    function, args = pickle.load(f)
function(*args)
"""


def _spawn_and_run(function, args, num_processes, mixed_precision, addr, port):
    """Subprocess spawn (not fork): each worker re-imports and runs the
    pickled function under the COORDINATOR/PROCESS_ID env contract."""
    with tempfile.TemporaryDirectory() as td:
        payload = os.path.join(td, "fn.pkl")
        with open(payload, "wb") as f:
            pickle.dump((function, tuple(args)), f)
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(textwrap.dedent(_WORKER_TEMPLATE).format(payload=payload))
        procs = []
        for rank in range(num_processes):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)  # disable TPU-tunnel sitecustomize
            env[env_var("MIXED_PRECISION")] = mixed_precision
            env[env_var("COORDINATOR_ADDRESS")] = f"{addr}:{port}"
            env[env_var("NUM_PROCESSES")] = str(num_processes)
            env[env_var("PROCESS_ID")] = str(rank)
            env[env_var("LOCAL_PROCESS_ID")] = str(rank)
            env[env_var("FORK_LAUNCHED")] = "1"
            procs.append(subprocess.Popen([sys.executable, script], env=env))
        code = 0
        for p in procs:
            p.wait()
            code = code or p.returncode
        if code:
            raise RuntimeError(f"notebook launcher worker failed with exit code {code}")
