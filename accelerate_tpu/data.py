"""Data layer: sharded samplers + device-feeding dataloaders.

Parity target: /root/reference/src/accelerate/data_loader.py (1,296 LoC):
``BatchSamplerShard`` (two sharding modes + even_batches wraparound),
``IterableDatasetShard``, ``SeedableRandomSampler``, ``DataLoaderShard``
(RNG sync at epoch start, one-batch-ahead prefetch flagging
``end_of_dataloader``, device placement), ``DataLoaderDispatcher`` (rank0
fetch + broadcast), ``skip_first_batches``.

TPU-native differences:
- "process" = host (JAX single-controller-per-host); each host loads its
  slice of the global batch and the global array is assembled with
  `jax.make_array_from_process_local_data` — no broadcast in the hot path.
- Static shapes: the final partial batch is PADDED to full size (repeating
  head samples, the reference's even_batches wraparound) and ``remainder``
  records the padding so `gather_for_metrics` can drop it. With
  ``even_batches=False`` the smaller final batch is yielded as-is (each
  distinct size triggers one extra XLA compile — documented).
- Works with torch DataLoaders (re-wrapped), map-style datasets, or any
  iterable of batches.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

from .logging import get_logger
from .state import GradientState, PartialState
from .utils.dataclasses import DataLoaderConfiguration, RNGType
from .utils.operations import (
    broadcast_object_list,
    concatenate,
    convert_to_jax,
    find_batch_size,
    make_global_batch,
    recursively_apply,
)
from .utils.random import default_keychain, synchronize_rng_states
from .telemetry import note_data_wait

logger = get_logger(__name__)


def _timed_next(iterator):
    """Advance the base iterator, attributing the host wait to telemetry's
    dataloader-wait bucket (a no-op check when no session is active)."""
    t0 = time.perf_counter()
    try:
        return next(iterator)
    finally:
        note_data_wait(time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Samplers (pure index math — reference data_loader.py:68-353)
# ---------------------------------------------------------------------------

class SeedableRandomSampler:
    """Deterministic shuffling sampler whose permutation depends only on
    (seed, epoch) (reference data_loader.py:68-100). Counter-based: resuming
    at epoch N reproduces the exact stream without replaying."""

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def __len__(self):
        return self.data_source_len

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        key = jax.random.key(self.seed)
        key = jax.random.fold_in(key, self.epoch)
        perm = np.asarray(jax.random.permutation(key, self.data_source_len))
        self.epoch += 1  # auto-advance like the reference (`set_epoch` also works)
        yield from perm.tolist()

    def state_dict(self) -> dict:
        return {"seed": self.seed, "epoch": self.epoch}

    def load_state_dict(self, state: dict):
        self.seed = state["seed"]
        self.epoch = state["epoch"]


class BatchSamplerShard:
    """Shards an iterable of index-batches across processes
    (reference data_loader.py:101-253).

    Two modes:
    - ``split_batches=True``: each global batch is split into
      ``num_processes`` chunks; batch size must divide evenly.
    - ``split_batches=False``: whole batches are round-robined — process i
      gets batches i, i+N, i+2N, ...

    ``even_batches=True`` guarantees all processes get the same number of
    equal-size batches by wrapping around to the beginning (duplicating head
    samples), exactly like the reference's :227-253.
    """

    def __init__(
        self,
        batch_sampler: Iterable[Sequence[int]],
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and hasattr(batch_sampler, "batch_size") and batch_sampler.batch_size % num_processes != 0:
            raise ValueError(
                f"To use `BatchSamplerShard` in `split_batches` mode, the batch size "
                f"({batch_sampler.batch_size}) needs to be a round multiple of the number "
                f"of processes ({num_processes})."
            )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)
        if self.batch_size is None and self.even_batches:
            raise ValueError(
                "You need to use `even_batches=False` when the batch sampler has no batch size."
            )

    def __len__(self):
        if self.split_batches:
            return len(self.batch_sampler)
        length = len(self.batch_sampler) // self.num_processes
        if len(self.batch_sampler) % self.num_processes == 0:
            return length
        if self.drop_last:
            return length
        if self.even_batches:
            return length + 1
        return length + 1 if self.process_index < len(self.batch_sampler) % self.num_processes else length

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __iter__(self):
        return self._iter_with_split() if self.split_batches else self._iter_with_no_split()

    def _iter_with_split(self):
        # Each FULL global batch contributes this process's contiguous window
        # [lo:hi]. A ragged final batch is either sliced as-is
        # (even_batches=False) or squared up by cycling samples from the
        # stream's head before slicing. Capability parity with reference
        # data_loader.py:187-208; written against the window formulation.
        per_proc = self.batch_size // self.num_processes
        lo, hi = per_proc * self.process_index, per_proc * (self.process_index + 1)
        head: list = []  # first batch seen, the wraparound source
        tail: list = []  # the stream's ragged final batch, if any
        for raw in self.batch_sampler:
            batch = list(raw)
            if not head:
                head = batch
            if len(batch) == self.batch_size:
                yield batch[lo:hi]
                tail = []  # a short batch only counts if it ends the stream
            else:
                tail = batch
        if self.drop_last or not tail:
            return
        if not self.even_batches:
            if len(tail) > lo:
                yield tail[lo:hi]
            return
        while len(tail) < self.batch_size:
            tail = tail + head
        yield tail[lo:hi]

    def _iter_with_no_split(self):
        # Stream the sampler in ROUNDS of `num_processes` whole batches;
        # process i owns slot i of every round. A round is emitted only once
        # its final batch is known full; the unfinished tail round (short
        # round and/or ragged last batch) is squared up from a pool of
        # head-of-stream samples so every process ends with the same number
        # of full batches. Capability parity with reference
        # data_loader.py:209-253; written against the round formulation.
        pool: list = []   # samples from the first round, cycled to fill the tail
        round_: list = [] # batches of the in-progress round
        for count, raw in enumerate(self.batch_sampler):
            batch = list(raw)
            if not self.drop_last and count < self.num_processes:
                pool.extend(batch)
            round_.append(batch)
            # Realign to index-based rounds: a round whose boundary batch was
            # short never flushes; drop its stale batches instead of letting
            # round_ grow unbounded and jam the == flush check below.
            del round_[: -(count % self.num_processes) - 1]
            if len(round_) == self.num_processes and (
                self.batch_size is None or len(batch) == self.batch_size
            ):
                yield round_[self.process_index]
                round_ = []
        if self.drop_last or not pool or not round_:
            return
        if not self.even_batches:
            if self.process_index < len(round_):
                yield round_[self.process_index]
            return
        # Square the tail round: top up the ragged last batch from the pool,
        # then synthesize whole batches from successive pool slices.
        while len(pool) < self.num_processes * self.batch_size:
            pool = pool + pool
        cursor = 0
        if len(round_[-1]) < self.batch_size:
            need = self.batch_size - len(round_[-1])
            round_[-1] = round_[-1] + pool[:need]
            cursor = need
        while len(round_) < self.num_processes:
            round_.append(pool[cursor : cursor + self.batch_size])
            cursor += self.batch_size
        yield round_[self.process_index]


class SimpleBatchSampler:
    """Minimal batch sampler over a sampler of indices (torch-free)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


class IterableDatasetShard:
    """Per-process slice of an iterable dataset (reference :257-353): buffer
    ``batch_size * num_processes`` items, keep this process's slice; final
    short window wraps around from the buffer head when even_batches."""

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches

    def set_epoch(self, epoch: int):
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self):
        # Window the raw stream into global-batch-sized chunks and emit this
        # process's contiguous sub-range of each window. The final short
        # window is squared up by cycling the first window's items
        # (even_batches) or sliced ragged. Capability parity with reference
        # data_loader.py:323-353; written against the window formulation.
        window = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        per_proc = window // self.num_processes
        lo, hi = per_proc * self.process_index, per_proc * (self.process_index + 1)
        head: Optional[list] = None
        buf: list = []
        for item in self.dataset:
            buf.append(item)
            if len(buf) == window:
                yield from buf[lo:hi]
                if head is None:
                    head = list(buf)
                buf = []
        if self.drop_last or not buf:
            return
        if not self.even_batches:
            yield from buf[lo:hi]
            return
        pad = head if head is not None else list(buf)
        while len(buf) < window:
            buf = buf + pad
        yield from buf[lo:hi]


# ---------------------------------------------------------------------------
# Collation
# ---------------------------------------------------------------------------

def default_collate(samples: list) -> Any:
    """Stack a list of samples into a batch pytree (numpy)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    arr = np.asarray(samples)
    return arr


# ---------------------------------------------------------------------------
# DataLoaders
# ---------------------------------------------------------------------------

class BaseDataLoader:
    """Common machinery: GradientState registration + end-of-iteration
    signaling via one-batch-ahead prefetch (reference DataLoaderAdapter +
    DataLoaderShard, data_loader.py:399-578)."""

    def __init__(self):
        self.gradient_state = GradientState()
        self.end_of_dataloader = False
        self.remainder = -1
        self._batches_yielded = 0

    def begin(self):
        self.end_of_dataloader = False
        self.gradient_state._add_dataloader(self)

    def end(self):
        # The singleton may have been reset (tests) before a suspended
        # generator is finalized; nothing to deregister then.
        if self.gradient_state.initialized:
            self.gradient_state._remove_dataloader(self)

    # -- mid-epoch resume support (≙ torchdata StatefulDataLoader contract) --
    def state_dict(self) -> dict:
        # After a completed epoch the next iteration starts fresh (epoch
        # counter already advanced in the generator's finally block).
        return {
            "batches_yielded": 0 if self.end_of_dataloader else self._batches_yielded,
            "iteration": getattr(self, "iteration", 0),
        }

    def load_state_dict(self, state: dict):
        self._skip_batches_on_next_iter = state.get("batches_yielded", 0)
        if "iteration" in state:
            self.iteration = state["iteration"]


class DataLoaderShard(BaseDataLoader):
    """Iterates a per-host loader and feeds global sharded arrays
    (reference data_loader.py:491-625).

    Per batch: convert (torch/np → np), pad the final ragged batch when
    ``even_batches`` (recording ``remainder``), place onto the mesh with
    batch-dim sharding over the data axes. RNG streams sync at epoch start.
    """

    def __init__(
        self,
        base_loader: Iterable,
        mesh=None,
        rng_types: Optional[list] = None,
        batch_size: Optional[int] = None,
        even_batches: bool = True,
        device_put: bool = True,
        skip_batches: int = 0,
        _drop_last: bool = False,
        batch_axes: tuple = ("replica", "data", "fsdp"),
        prefetch_depth: int = 0,
    ):
        super().__init__()
        self.base_loader = base_loader
        self.prefetch_depth = prefetch_depth
        self.mesh = mesh
        self.rng_types = rng_types or []
        self.batch_size = batch_size
        self.even_batches = even_batches
        self.device_put = device_put
        self.skip_batches = skip_batches
        self.batch_axes = batch_axes
        self._drop_last = _drop_last
        self._skip_batches_on_next_iter = 0
        self.iteration = 0

    def set_epoch(self, epoch: int):
        self.iteration = epoch
        for obj in (self.base_loader, getattr(self.base_loader, "dataset", None),
                    getattr(self.base_loader, "sampler", None),
                    getattr(self.base_loader, "batch_sampler", None)):
            if obj is not None and hasattr(obj, "set_epoch"):
                obj.set_epoch(epoch)

    def _global_batch_size(self) -> Optional[int]:
        if self.batch_size is None:
            return None
        return self.batch_size * PartialState().num_processes

    def _finalize_batch(self, batch, pad_to: Optional[int]):
        batch = convert_to_jax(batch)
        bs = find_batch_size(batch)
        if pad_to is not None and bs is not None and bs < pad_to:
            if self.even_batches:
                self.remainder = bs

                def _pad(t):
                    if not hasattr(t, "shape") or t.ndim == 0 or t.shape[0] != bs:
                        return t
                    reps = [t]
                    missing = pad_to - bs
                    while missing > 0:
                        take = min(missing, bs)
                        reps.append(t[:take])
                        missing -= take
                    return np.concatenate([np.asarray(r) for r in reps], axis=0)

                batch = recursively_apply(_pad, batch, test_type=lambda x: hasattr(x, "shape"))
        if self.device_put and self.mesh is not None:
            batch = make_global_batch(batch, self.mesh, batch_axes=self.batch_axes)
        return batch

    def __iter__(self):
        self.begin()
        self._batches_yielded = 0
        skip = self.skip_batches + self._skip_batches_on_next_iter
        self._skip_batches_on_next_iter = 0
        if self.rng_types:
            synchronize_rng_states(self.rng_types)
        self.set_epoch(self.iteration)
        # remainder = number of REAL samples in the final (padded) global
        # batch; consumed by gather_for_metrics to drop wraparound duplicates
        # (reference DataLoaderStateMixin, data_loader.py:356-397).
        self.remainder = -1
        tdl = self.total_dataset_length
        gbs = self._global_batch_size()
        if self.even_batches and tdl is not None and gbs:
            rem = tdl % gbs
            if rem != 0:
                self.remainder = rem
        per_proc = self.batch_size
        prefetcher = None
        try:
            iterator = iter(self.base_loader)
            if self.prefetch_depth > 1:
                # native host prefetch ring: batch assembly overlaps device
                # compute (runtime/prefetch.py); dict-of-array batches only
                from .runtime.prefetch import HostPrefetcher

                prefetcher = HostPrefetcher(iterator, depth=self.prefetch_depth)
                iterator = iter(prefetcher)
            # one-batch-ahead prefetch to flag end_of_dataloader on the LAST
            # yield (reference :555-578)
            try:
                current = _timed_next(iterator)
            except StopIteration:
                self.end_of_dataloader = True
                return
            batch_index = 0
            while True:
                try:
                    upcoming = _timed_next(iterator)
                    at_end = False
                except StopIteration:
                    upcoming = None
                    at_end = True
                if batch_index >= skip:
                    if at_end:
                        self.end_of_dataloader = True
                        self.gradient_state._set_sync_gradients(
                            self.gradient_state.sync_gradients
                            or self.gradient_state.sync_with_dataloader
                        )
                    self._batches_yielded += 1
                    # conversion + padding + device placement are loader work
                    # too — time them into the same dataloader-wait bucket
                    t0 = time.perf_counter()
                    ready = self._finalize_batch(current, per_proc)
                    note_data_wait(time.perf_counter() - t0)
                    yield ready
                if at_end:
                    return
                current = upcoming
                batch_index += 1
        finally:
            if prefetcher is not None:
                # unblock + drop the producer thread even when the consumer
                # abandons the epoch early (max_steps / early stop)
                prefetcher.close()
            self.iteration += 1
            self.end()

    def __len__(self):
        return len(self.base_loader)

    @property
    def total_batch_size(self):
        return self._global_batch_size()

    @property
    def total_dataset_length(self):
        ds = getattr(self.base_loader, "dataset", None)
        return len(ds) if ds is not None and hasattr(ds, "__len__") else None


class DataLoaderDispatcher(BaseDataLoader):
    """Rank-0 fetches, broadcasts structure + data, every host slices its
    share (reference data_loader.py:672-852). Only useful for streaming/
    non-deterministic sources where per-host sharding can't be replicated;
    the default path (DataLoaderShard) avoids this broadcast entirely.
    """

    def __init__(
        self,
        base_loader: Iterable,
        mesh=None,
        batch_size: Optional[int] = None,
        even_batches: bool = True,
        skip_batches: int = 0,
        batch_axes: tuple = ("replica", "data", "fsdp"),
    ):
        super().__init__()
        self.base_loader = base_loader
        self.mesh = mesh
        self.batch_size = batch_size
        self.even_batches = even_batches
        self.skip_batches = skip_batches
        self.batch_axes = batch_axes
        self._skip_batches_on_next_iter = 0
        self.iteration = 0

    def __iter__(self):

        state = PartialState()
        self.begin()
        self._batches_yielded = 0
        skip = self.skip_batches + self._skip_batches_on_next_iter
        self._skip_batches_on_next_iter = 0
        self.remainder = -1
        try:
            iterator = iter(self.base_loader) if state.is_main_process else None
            batch_index = 0
            stop = False
            t0 = time.perf_counter()
            current = self._fetch_and_share(iterator, state)
            note_data_wait(time.perf_counter() - t0)
            if current is None:
                self.end_of_dataloader = True
                return
            while True:
                t0 = time.perf_counter()
                upcoming = self._fetch_and_share(iterator, state)
                note_data_wait(time.perf_counter() - t0)
                at_end = upcoming is None
                if batch_index >= skip:
                    if at_end:
                        self.end_of_dataloader = True
                    self._batches_yielded += 1
                    yield current
                if at_end:
                    return
                current = upcoming
                batch_index += 1
        finally:
            self.iteration += 1
            self.end()

    def _fetch_and_share(self, iterator, state):
        # main process reads the batch; all processes learn the structure
        # (+ the real row count of a padded ragged tail), then the global
        # array is built from main's data only.
        main_err = None
        if state.is_main_process:
            try:
                batch = convert_to_jax(next(iterator))
                batch, real_rows = self._pad_ragged_tail(batch, state)
                info = [_tree_meta(batch), real_rows]
            except StopIteration:
                info = [None, None]
            except Exception as e:
                # ANY main-only raise (ragged-tail rejection, a dataset
                # __getitem__ bug, IO errors...) would leave every other rank
                # parked in the broadcast below — a silent desync. Ship the
                # error so ALL ranks raise together; main re-raises the
                # original with its traceback.
                main_err = e
                info = [("__dispatch_error__", f"{type(e).__name__}: {e}"), None]
        else:
            batch, info = None, [None, None]
        if state.num_processes > 1:
            info = broadcast_object_list(info)
        if isinstance(info[0], tuple) and len(info[0]) == 2 and info[0][0] == "__dispatch_error__":
            if main_err is not None:
                raise main_err
            raise RuntimeError(f"dispatch main process failed: {info[0][1]}")
        if info[0] is None:
            return None
        if info[1] is not None:
            # consumed by gather_for_metrics at end_of_dataloader
            self.remainder = info[1]
        if state.num_processes > 1:
            batch = _scatter_from_main(batch, info[0], self.mesh, state, self.batch_axes)
        elif self.mesh is not None:
            batch = make_global_batch(batch, self.mesh, batch_axes=self.batch_axes)
        return batch

    def _pad_ragged_tail(self, batch, state):
        """Square up a ragged final global batch by repeating its head rows
        (reference dispatch even_batches semantics) so every process can take
        an equal slice and shapes stay static. Returns (batch, real_rows) —
        real_rows is None when nothing was padded."""
        rows = find_batch_size(batch)
        if rows is None:
            return batch, None
        if self.batch_size is not None:
            target = self.batch_size * state.num_processes
        else:
            target = -(-rows // state.num_processes) * state.num_processes
        if rows >= target:
            return batch, None
        if not self.even_batches:
            raise ValueError(
                f"dispatch_batches with even_batches=False cannot shard a ragged "
                f"final batch of {rows} rows across {state.num_processes} processes; "
                "use drop_last=True or keep even_batches=True"
            )

        def _pad(t):
            if getattr(t, "ndim", 0) == 0 or t.shape[0] != rows:
                return t
            t = np.asarray(t)
            reps, missing = [t], target - rows
            while missing > 0:
                take = min(missing, rows)
                reps.append(t[:take])
                missing -= take
            return np.concatenate(reps, axis=0)

        padded = recursively_apply(_pad, batch, test_type=lambda x: hasattr(x, "shape"))
        return padded, rows

    def __len__(self):
        return len(self.base_loader)


def _tree_meta(batch):
    return jax.tree_util.tree_map(
        lambda t: (tuple(t.shape), str(t.dtype)) if hasattr(t, "shape") else t, batch
    )


def _is_meta_leaf(x):
    """A (shape, dtype) entry produced by _tree_meta — must be treated as a
    leaf when tree-mapping over the meta structure."""
    return (
        isinstance(x, tuple)
        and len(x) == 2
        and isinstance(x[0], tuple)
        and isinstance(x[1], str)
    )


def _scatter_from_main(batch, meta, mesh, state, batch_axes):
    """Dispatch-mode scatter: rank 0 read the FULL global batch; every host
    receives it over DCN, keeps only its own contiguous per-process slice of
    the batch dimension, and contributes that slice to the assembled global
    array (reference data_loader.py:731-852 rank0-fetch + slice_fn)."""
    from .utils.operations import broadcast

    def _one(leaf_meta, leaf):
        if not isinstance(leaf_meta, tuple) or len(leaf_meta) != 2:
            # non-array leaf: main's value was shipped in the meta itself
            return leaf_meta if leaf is None else leaf
        shape, dtype = leaf_meta
        if state.is_main_process:
            data = np.asarray(leaf)
        else:
            data = np.zeros(shape, dtype=np.dtype(dtype))
        data = np.asarray(broadcast(data))
        if data.ndim == 0:
            return data  # scalar: replicated, nothing to slice
        rows = data.shape[0]
        if rows % state.num_processes != 0:
            raise ValueError(
                f"dispatch_batches requires the global batch dimension ({rows}) "
                f"to divide evenly across {state.num_processes} processes"
            )
        per = rows // state.num_processes
        return data[state.process_index * per : (state.process_index + 1) * per]

    if state.is_main_process:
        local = jax.tree_util.tree_map(_one, meta, batch, is_leaf=_is_meta_leaf)
    else:
        local = jax.tree_util.tree_map(lambda m: _one(m, None), meta, is_leaf=_is_meta_leaf)
    if mesh is not None:
        return make_global_batch(local, mesh, batch_axes=batch_axes)
    return local


# ---------------------------------------------------------------------------
# factory (reference prepare_data_loader, data_loader.py:913-1157)
# ---------------------------------------------------------------------------

def prepare_data_loader(
    dataloader,
    mesh=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch: Optional[Callable] = None,
    use_seedable_sampler: bool = True,
    data_seed: int = 0,
    config: Optional[DataLoaderConfiguration] = None,
):
    """Wrap any of (torch DataLoader | map-style dataset + batch_size |
    iterable of batches) into a DataLoaderShard/Dispatcher feeding the mesh."""
    state = PartialState()
    num_processes = num_processes if num_processes is not None else state.num_processes
    process_index = process_index if process_index is not None else state.process_index
    if config is not None:
        split_batches = config.split_batches
        dispatch_batches = config.dispatch_batches
        even_batches = config.even_batches
        use_seedable_sampler = config.use_seedable_sampler

    if dispatch_batches:
        per_bs = _find_batch_size_attr(dataloader, split_batches, num_processes)
        base = dataloader
        if not split_batches and num_processes > 1:
            # reference dispatch semantics: main fetches ONE GLOBAL batch of
            # per_process_bs x N per step and each process takes its slice —
            # re-batch the source instead of padding every per-process fetch
            # N-fold (which would hand trailing ranks pure padding)
            if type(dataloader) is DataLoader:
                base = DataLoader(
                    dataloader.dataset,
                    batch_size=dataloader.batch_size * num_processes,
                    shuffle=dataloader.shuffle,
                    drop_last=dataloader.drop_last,
                    collate_fn=dataloader.collate_fn,
                    seed=dataloader.seed,
                )
            elif per_bs is not None:
                # torch loaders / DataLoader subclasses / anything else:
                # concatenate N consecutive source batches per global fetch,
                # preserving the source's own iteration logic
                base = _GlobalRebatch(dataloader, num_processes)
        return DataLoaderDispatcher(
            base,
            # put_on_device=False keeps batches host-side (each process
            # holds its slice as numpy), exactly like the shard path
            mesh=mesh if put_on_device else None,
            batch_size=per_bs,
            even_batches=even_batches,
        )

    base_loader, per_proc_bs = _shard_loader(
        dataloader, num_processes, process_index, split_batches, even_batches,
        use_seedable_sampler, data_seed,
    )
    return DataLoaderShard(
        base_loader,
        mesh=mesh,
        rng_types=rng_types,
        batch_size=per_proc_bs,
        even_batches=even_batches,
        device_put=put_on_device,
        prefetch_depth=config.prefetch_depth if config is not None else 0,
    )


class _GlobalRebatch:
    """Concatenate N consecutive source batches into one global batch (the
    dispatch-mode re-batch for loaders we cannot rebuild: torch DataLoaders,
    DataLoader subclasses, generic iterables). The source's own sampling /
    collation / augmentation logic runs untouched; only the tail can come up
    short (handled by the dispatcher's ragged-tail padding)."""

    def __init__(self, base, n: int):
        self.base = base
        self.n = int(n)

    def __iter__(self):
        chunk = []
        for batch in self.base:
            chunk.append(batch)
            if len(chunk) == self.n:
                yield _concat_batches(chunk)
                chunk = []
        if chunk:
            yield _concat_batches(chunk)

    def __len__(self):
        return -(-len(self.base) // self.n)


def _concat_batches(batches: list):
    if len(batches) == 1:
        return batches[0]
    first = batches[0]
    return jax.tree_util.tree_map(
        lambda *leaves: np.concatenate([np.asarray(l) for l in leaves], axis=0)
        if getattr(leaves[0], "ndim", 0) >= 1
        else leaves[0],
        first,
        *batches[1:],
    )


def _find_batch_size_attr(dataloader, split_batches, num_processes):
    bs = getattr(dataloader, "batch_size", None)
    if bs is None:
        bsampler = getattr(dataloader, "batch_sampler", None)
        bs = getattr(bsampler, "batch_size", None)
    if bs is None:
        return None
    return bs // num_processes if split_batches else bs


def _shard_loader(dataloader, num_processes, process_index, split_batches, even_batches,
                  use_seedable_sampler, data_seed):
    """Rebuild the loader so this process only reads its own index shard."""
    # Case 1: torch DataLoader → re-wrap dataset with sharded batch sampler
    is_torch_loader = type(dataloader).__module__.startswith("torch.utils.data")
    if is_torch_loader:
        dataset = dataloader.dataset
        batch_sampler = dataloader.batch_sampler
        collate = dataloader.collate_fn
        if batch_sampler is None:  # iterable-style torch dataset
            shard = IterableDatasetShard(
                dataset,
                batch_size=dataloader.batch_size,
                drop_last=dataloader.drop_last,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
                even_batches=even_batches,
            )
            return _SimpleLoader(shard, dataloader.batch_size, collate), dataloader.batch_size
        sampler = batch_sampler.sampler
        if use_seedable_sampler and type(sampler).__name__ == "RandomSampler":
            sampler = SeedableRandomSampler(len(dataset), seed=data_seed)
        base_bsampler = SimpleBatchSampler(sampler, batch_sampler.batch_size, batch_sampler.drop_last)
        sharded = BatchSamplerShard(
            base_bsampler, num_processes, process_index, split_batches, even_batches
        )
        per_proc = batch_sampler.batch_size // num_processes if split_batches else batch_sampler.batch_size
        return _MapLoader(dataset, sharded, collate), per_proc

    # Case 2: our own DataLoader
    if isinstance(dataloader, DataLoader):
        sampler = dataloader.sampler
        if use_seedable_sampler and dataloader.shuffle and not isinstance(sampler, SeedableRandomSampler):
            sampler = SeedableRandomSampler(len(dataloader.dataset), seed=data_seed)
        base_bsampler = SimpleBatchSampler(sampler, dataloader.batch_size, dataloader.drop_last)
        sharded = BatchSamplerShard(
            base_bsampler, num_processes, process_index, split_batches, even_batches
        )
        per_proc = dataloader.batch_size // num_processes if split_batches else dataloader.batch_size
        return _MapLoader(dataloader.dataset, sharded, dataloader.collate_fn), per_proc

    # Case 3: raw iterable of ready-made batches — shard by round-robin
    return _RoundRobinLoader(dataloader, num_processes, process_index), None


class _MapLoader:
    """Map-style dataset + batch sampler + collate — the per-host loader."""

    def __init__(self, dataset, batch_sampler, collate_fn=None):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate_fn = collate_fn or default_collate

    def __iter__(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __len__(self):
        return len(self.batch_sampler)

    def set_epoch(self, epoch):
        for obj in (self.dataset, self.batch_sampler, getattr(self.batch_sampler, "batch_sampler", None)):
            if obj is not None and hasattr(obj, "set_epoch"):
                obj.set_epoch(epoch)
        sampler = getattr(getattr(self.batch_sampler, "batch_sampler", None), "sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)


class _SimpleLoader:
    def __init__(self, iterable_shard, batch_size, collate_fn=None):
        self.dataset = iterable_shard
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate

    def __iter__(self):
        buf = []
        for item in self.dataset:
            buf.append(item)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf:
            yield self.collate_fn(buf)

    def set_epoch(self, epoch):
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)


class _RoundRobinLoader:
    def __init__(self, iterable, num_processes, process_index):
        self.iterable = iterable
        self.num_processes = num_processes
        self.process_index = process_index

    def __iter__(self):
        for i, batch in enumerate(self.iterable):
            if i % self.num_processes == self.process_index:
                yield batch

    def __len__(self):
        n = len(self.iterable)
        extra = 1 if n % self.num_processes > self.process_index else 0
        return n // self.num_processes + extra

    def set_epoch(self, epoch):
        if hasattr(self.iterable, "set_epoch"):
            self.iterable.set_epoch(epoch)


class DataLoader:
    """Torch-free map-style dataloader (construct, then `accelerator.prepare`).

    Datasets are anything with ``__getitem__``/``__len__`` yielding pytrees.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate
        self.seed = seed
        if shuffle:
            self.sampler = SeedableRandomSampler(len(dataset), seed=seed)
        else:
            self.sampler = range(len(dataset))

    def __iter__(self):
        bsampler = SimpleBatchSampler(self.sampler, self.batch_size, self.drop_last)
        for indices in bsampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __len__(self):
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


# ---------------------------------------------------------------------------
# skip_first_batches (reference data_loader.py:1160-1253)
# ---------------------------------------------------------------------------

def skip_first_batches(dataloader, num_batches: int = 0):
    """Resume mid-epoch: a loader that skips the first ``num_batches``."""
    if isinstance(dataloader, (DataLoaderShard, DataLoaderDispatcher)):
        import copy

        new = copy.copy(dataloader)
        new.skip_batches = dataloader.skip_batches + num_batches
        return new

    class _Skipper:
        def __init__(self, inner, n):
            self.inner = inner
            self.n = n
            self.dataset = getattr(inner, "dataset", None)

        def __iter__(self):
            for i, batch in enumerate(self.inner):
                if i >= self.n:
                    yield batch

        def __len__(self):
            return max(0, len(self.inner) - self.n)

    return _Skipper(dataloader, num_batches)
