"""Process/device runtime singletons: PartialState, AcceleratorState,
GradientState.

Parity target: /root/reference/src/accelerate/state.py (1,234 LoC). Same
singleton-shared-``__dict__`` design (state.py:82,153) so every instance
anywhere in the program sees one runtime. What changes on TPU:

- backend selection + ``init_process_group`` (state.py:709-766) becomes
  `jax.distributed.initialize` (only on multi-host) + `jax.Mesh` construction;
- "device" is a mesh of devices, not one cuda index; rank topology comes from
  `jax.process_index/process_count` (hosts) and `jax.device_count` (chips);
- `wait_for_everyone` (state.py:342) becomes a sync over global devices.
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager
from functools import wraps
from typing import Any, Callable, Optional

import jax

from .parallel.mesh import build_mesh, mesh_shape_dict
from .utils.dataclasses import (
    DistributedType,
    GradientAccumulationPlugin,
    MixedPrecisionConfig,
    PrecisionType,
    ShardingConfig,
    ShardingStrategy,
)
from .utils.environment import (
    get_coordinator_address,
    get_env,
    get_flag,
    get_num_processes_env,
    get_process_id,
    parse_choice_from_env,
)

logger = logging.getLogger(__name__)

_jax_distributed_initialized = False


def _maybe_init_jax_distributed():
    """Initialize jax.distributed exactly once, iff launch env asks for it.

    The launcher writes COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID
    (utils/launch env contract, ≙ reference MASTER_ADDR/WORLD_SIZE/RANK).
    Single-host runs skip this entirely — jax sees local devices directly.
    """
    global _jax_distributed_initialized
    if _jax_distributed_initialized:
        return
    coord = get_coordinator_address()
    nproc = get_num_processes_env()
    if coord and nproc and nproc > 1:
        # Cross-process collectives on the CPU backend need gloo (the
        # debug/gloo-on-localhost test path, reference launchers.py:269).
        # Setting it only configures the CPU client, so it is safe to set
        # unconditionally — also covers hosts where CPU is the default
        # platform without JAX_PLATFORMS being set.
        try:
            jax.config.update("jax_cpu_collectives", "gloo")
        except Exception:  # pragma: no cover - older jaxlib
            pass
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=get_process_id() or 0,
        )
        _jax_distributed_initialized = True


class _SharedDict(dict):
    """All instances of a state class share one dict (reference state.py:38-82;
    we use a plain class-level dict — the reference's thread-local variant
    existed only for torch_xla's one-process-per-device spawn model, which JAX
    does not use: one process drives all local chips)."""


class PartialState:
    """Topology + process-control singleton (reference state.py:114).

    Knows nothing about mixed precision or sharding strategy — just who we
    are (process_index / num_processes), what devices exist, and process
    coordination primitives.
    """

    _shared_state = _SharedDict()

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        self._cpu = cpu or parse_choice_from_env("JAX_PLATFORMS", "") == "cpu"
        self.debug = get_flag("DEBUG_MODE")
        _maybe_init_jax_distributed()

        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        # All processes on one host would need distinct local indices; JAX
        # runs one process per host, so local index is 0 unless the launcher
        # says otherwise (CPU-sim multi-proc testing).
        self.local_process_index = int(get_env("LOCAL_PROCESS_ID", 0))
        self.devices = jax.local_devices()
        self.device = self.devices[0]
        backend = jax.default_backend()
        self.backend = backend
        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif backend == "cpu":
            self.distributed_type = (
                DistributedType.CPU_SIM if jax.device_count() > 1 else DistributedType.NO
            )
        elif jax.device_count() > 1:
            self.distributed_type = DistributedType.TPU
        else:
            self.distributed_type = DistributedType.NO
        self.fork_launched = get_flag("FORK_LAUNCHED")

    # -- lifecycle ---------------------------------------------------------

    @property
    def initialized(self) -> bool:
        return "distributed_type" in self.__dict__

    @classmethod
    def _reset_state(cls):
        """Tear down for tests (reference state.py:1189)."""
        cls._shared_state.clear()

    # -- topology ----------------------------------------------------------

    @property
    def num_devices(self) -> int:
        return jax.device_count()

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def use_distributed(self) -> bool:
        return self.num_devices > 1 or self.num_processes > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # -- coordination ------------------------------------------------------

    def wait_for_everyone(self):
        """Cross-host barrier (reference state.py:342). On a single process
        this is a device sync (flush pending async dispatch)."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")
        else:
            (jax.device_put(0) + 0).block_until_ready()

    @contextmanager
    def main_process_first(self):
        """Main process runs the body first, others wait (state.py:477)."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def local_main_process_first(self):
        if not self.is_local_main_process:
            self.wait_for_everyone()
        yield
        if self.is_local_main_process:
            self.wait_for_everyone()

    def on_main_process(self, function: Callable = None):
        """Decorator: run only on the main process (state.py:518)."""

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_local_main_process(self, function: Callable = None):
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_last_process(self, function: Callable):
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable = None, process_index: int = None):
        if function is None:
            return lambda f: self.on_process(f, process_index)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return wrapper

    def on_local_process(self, function: Callable = None, local_process_index: int = None):
        if function is None:
            return lambda f: self.on_local_process(f, local_process_index)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return wrapper

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/dict/array evenly across processes (state.py:388).

        With ``apply_padding`` the last process's share is padded with the
        final element so all shares are equal-length (needed before gather).
        """
        if self.num_processes == 1:
            yield inputs
            return
        length = len(inputs)
        if isinstance(inputs, dict):
            length = len(inputs[list(inputs.keys())[0]])
            if not all(len(v) == length for v in inputs.values()):
                raise ValueError("All dict values must have the same length")
        num_samples_per_process, num_extras = divmod(length, self.num_processes)
        start = self.process_index * num_samples_per_process + min(self.process_index, num_extras)
        end = start + num_samples_per_process + (1 if self.process_index < num_extras else 0)

        def _split(obj):
            if isinstance(obj, dict):
                return {k: _split(v) for k, v in obj.items()}
            result = obj[start:end]
            if apply_padding:
                whole = num_samples_per_process + (1 if num_extras > 0 else 0)
                if hasattr(result, "shape"):
                    import numpy as np

                    while result.shape[0] < whole:
                        result = np.concatenate([result, result[-1:]], axis=0)
                else:
                    result = list(result) + [result[-1]] * (whole - len(result))
            return result

        yield _split(inputs)

    # -- telemetry heartbeat ----------------------------------------------

    def publish_heartbeat(self, step: int):
        """Record this process's training progress in the shared state dict.

        The slot lives in ``_shared_state`` (the dict every PartialState
        instance aliases), so the telemetry watchdog's monitor thread — or
        any other observer — reads the latest beat through a fresh
        ``PartialState()`` with zero coupling to the training loop. The
        step counter is monotonic per run; the timestamp is
        ``time.monotonic()`` (immune to wall-clock jumps)."""
        self.__dict__["telemetry_heartbeat"] = (int(step), time.monotonic())

    @property
    def heartbeat(self):
        """``(step, monotonic_time)`` of the last published heartbeat, or
        None when nothing has beaten yet."""
        return self.__dict__.get("telemetry_heartbeat")

    def set_device(self):  # pragma: no cover - parity no-op
        """JAX owns device selection; kept for API parity."""

    def print(self, *args, **kwargs):
        if self.is_local_main_process:
            print(*args, **kwargs)

    def __repr__(self):
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local process index: {self.local_process_index}\n"
            f"Device count: {self.num_devices}\n"
            f"Backend: {self.backend}\n"
        )


class AcceleratorState:
    """Adds mixed precision + sharding/mesh on top of PartialState
    (reference state.py:815)."""

    _shared_state = _SharedDict()

    def __init__(
        self,
        mixed_precision: str | None = None,
        cpu: bool = False,
        sharding_config: Optional[ShardingConfig] = None,
        _from_accelerator: bool = False,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if mixed_precision is not None and mixed_precision != self.mixed_precision:
                raise ValueError(
                    "AcceleratorState already initialized with "
                    f"mixed_precision={self.mixed_precision!r}; create the Accelerator "
                    "before any other AcceleratorState() use, or _reset_state() first."
                )
            if sharding_config is not None and sharding_config != self.sharding_config:
                raise ValueError(
                    "AcceleratorState already initialized with a different "
                    f"sharding_config ({self.sharding_config}); create the Accelerator "
                    "before any other AcceleratorState() use, or _reset_state() first."
                )
            return
        self._partial = PartialState(cpu, **kwargs)
        mp = mixed_precision or get_env("MIXED_PRECISION", "no")
        self.precision = MixedPrecisionConfig(mode=PrecisionType(mp))
        self.sharding_config = sharding_config or _sharding_config_from_env()
        self.mesh = build_mesh(self.sharding_config.resolve(jax.device_count()))
        self.initialized_from_accelerator = _from_accelerator

    @property
    def initialized(self) -> bool:
        return "precision" in self.__dict__

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False):
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()
            GradientState._reset_state()

    @property
    def mixed_precision(self) -> str:
        return self.precision.mode.value

    @property
    def mesh_shape(self) -> dict:
        return mesh_shape_dict(self.mesh)

    def __getattr__(self, name):
        # Delegate topology/coordination to PartialState (reference does the
        # same via shared dict; we compose instead).
        if name in ("_partial",) or name.startswith("__"):
            raise AttributeError(name)
        partial = self.__dict__.get("_partial")
        if partial is None:
            raise AttributeError(
                f"AcceleratorState has no attribute {name!r} (not initialized)"
            )
        return getattr(partial, name)

    def __repr__(self):
        return (
            repr(self._partial)
            + f"Mixed precision: {self.mixed_precision}\n"
            + f"Mesh: {self.mesh_shape}\n"
        )


def _sharding_config_from_env() -> ShardingConfig:
    """Build ShardingConfig from launcher env vars (config cascade level 2;
    reference plugins read FSDP_*/MEGATRON_LM_* envs in __post_init__)."""
    kwargs = {}
    mapping = {
        "STRATEGY": ("strategy", str),
        "DATA_PARALLEL": ("data_parallel", int),
        "FSDP": ("fsdp", int),
        "TENSOR_PARALLEL": ("tensor_parallel", int),
        "SEQUENCE_PARALLEL": ("sequence_parallel", int),
        "EXPERT_PARALLEL": ("expert_parallel", int),
        "PIPELINE_PARALLEL": ("pipeline_parallel", int),
        "REPLICA": ("replica", int),
        "GRAD_COMPRESSION": ("grad_compression_dtype", str),
    }
    for env_name, (field_name, cast) in mapping.items():
        v = get_env(env_name)
        if v:  # unset AND empty both mean "not configured" (launcher stomps
            #    GRAD_COMPRESSION with "" to kill stale inherited values)
            kwargs[field_name] = cast(v)
    return ShardingConfig(**kwargs)


class GradientState:
    """Gradient-accumulation bookkeeping singleton (reference state.py:1111).

    ``sync_gradients`` tells wrappers whether this micro-step is a boundary;
    ``remainder`` records how many tail samples of the last batch are padding
    (consumed by ``gather_for_metrics``); active dataloaders register here so
    end-of-epoch forces a sync (reference state.py:1216-1229).
    """

    _shared_state = _SharedDict()

    def __init__(self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.plugin_kwargs = (
                gradient_accumulation_plugin.to_kwargs()
                if gradient_accumulation_plugin is not None
                else {}
            )
            self._is_xla_gradients_synced = True
        if gradient_accumulation_plugin is not None and self.plugin_kwargs != gradient_accumulation_plugin.to_kwargs():
            self.plugin_kwargs = gradient_accumulation_plugin.to_kwargs()

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self.__dict__

    @classmethod
    def _reset_state(cls):
        cls._shared_state.clear()

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", True)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def sync_each_batch(self) -> bool:
        return self.plugin_kwargs.get("sync_each_batch", False)

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _add_dataloader(self, dataloader):
        self.dataloader_references.append(dataloader)
        self.active_dataloader = dataloader

    def _remove_dataloader(self, dataloader):
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    def _set_sync_gradients(self, value: bool):
        self.sync_gradients = value

    def __repr__(self):
        return (
            f"Sync Gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation plugin: {self.plugin_kwargs}\n"
        )
