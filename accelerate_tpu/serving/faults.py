"""Deterministic fault injection for the serving engine.

A scheduler that only ever sees healthy traffic is untested where it
matters: the claim worth defending is that the engine **degrades
gracefully** — bounded tenant interference, every request reaching a
definite outcome, zero recompiles — while things go wrong. This module
makes "things go wrong" reproducible:

- **delayed steps** — injected sleeps before decode or prefill
  dispatches (a straggler host, a noisy neighbor on the chip);
- **page exhaustion** — the injector allocates and *holds* pages from
  the engine's allocator for a step window, forcing the overcommit /
  preemption / shed machinery to run without needing a giant traffic
  burst;
- **poisoned requests** — a request whose ``on_token`` callback raises
  (a buggy downstream consumer); the engine must contain the blast
  radius to that one request (outcome ``cancelled``), never the loop;
- **tenant storms** — a callable fired at a chosen engine step,
  typically a burst of ``submit()`` calls mid-flight (the mixed-tenant
  isolation tests ride this);
- **network faults** — connection-refused, slow-replica latency, and
  mid-stream drops injected at the *router's* transport layer
  (``serving/router.py`` consults ``before_connect`` /
  ``on_stream_event``): the same injector that drove the single-engine
  scheduler drills drives the multi-replica failover and kill drills;
- **wrong tokens** — silent content corruption injected at the *replica
  server's* emit path (``ReplicaServer(faults=...)`` consults
  ``corrupt_token``): valid framing, wrong answer — the failure class
  only the synthetic canary (``telemetry/canary.py``) catches.

Everything is **seeded and scripted**: probabilistic faults draw from a
private ``random.Random(seed)``, scheduled faults key on the engine's
own ``step_count`` — the same seed and traffic replay the same fault
sequence, so a failing burst test is a repro, not an anecdote. The
module is plain python (no jax/flax — locked by tests/test_imports.py):
the engine consults it with one attribute check per step when faults
are off.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional


class PoisonError(RuntimeError):
    """What a poisoned request's ``on_token`` callback raises."""


class StreamDropped(ConnectionError):
    """A replica's token stream ended mid-flight without a terminal
    event — what the router sees when a replica dies while streaming
    (and what the ``drop_stream`` fault injects)."""


def poison_on_token(token, req):
    """Drop-in ``on_token`` callback that blows up on the first token —
    the canonical poisoned request. The engine must cancel the request
    and keep serving."""
    raise PoisonError(f"poisoned request {req.id} (token {token})")


class FaultInjector:
    """Scripted + seeded fault schedule, consulted by ``ServingEngine``.

    Wire it with ``ServingEngine(..., faults=FaultInjector(seed=0)
    .delay_decode(every=4, delay_s=0.002))``. Hooks the engine calls:
    ``on_step(engine)`` once per scheduler iteration (storms fire,
    page squeezes arm/release), ``before_decode(engine)`` /
    ``before_prefill(engine)`` ahead of the respective dispatches
    (delays sleep). ``log`` records every fired fault as
    ``(step, kind, detail)`` so tests assert the schedule actually ran.
    """

    def __init__(self, seed: int = 0, sleep_fn: Callable[[float], None] = time.sleep):
        self.rng = random.Random(seed)
        self._sleep = sleep_fn
        self._delays: list = []     # dicts: phase/every/prob/delay_s/start/stop
        self._squeezes: list = []   # dicts: at_step/pages/hold_steps/held
        self._storms: list = []     # (at_step, fn, fired)
        self._net: list = []        # dicts: kind/replica/count/prob/after_tokens
        self._net_calls = 0         # connection-attempt counter (network clock)
        self.log: list = []         # (step, kind, detail)

    # -- schedule builders (chainable) -------------------------------------

    def delay_decode(self, *, every: Optional[int] = None,
                     prob: Optional[float] = None, delay_s: float = 0.002,
                     start: int = 0, stop: Optional[int] = None) -> "FaultInjector":
        """Sleep ``delay_s`` before decode dispatches — every Nth step,
        or with probability ``prob`` per step (seeded)."""
        if (every is None) == (prob is None):
            raise ValueError("pass exactly one of every= / prob=")
        self._delays.append(dict(phase="decode", every=every, prob=prob,
                                 delay_s=float(delay_s), start=start, stop=stop))
        return self

    def delay_prefill(self, *, every: Optional[int] = None,
                      prob: Optional[float] = None, delay_s: float = 0.002,
                      start: int = 0, stop: Optional[int] = None) -> "FaultInjector":
        """Sleep before prefill-chunk dispatches (makes prefill cost —
        and therefore tenant interference — controlled and visible)."""
        if (every is None) == (prob is None):
            raise ValueError("pass exactly one of every= / prob=")
        self._delays.append(dict(phase="prefill", every=every, prob=prob,
                                 delay_s=float(delay_s), start=start, stop=stop))
        return self

    def squeeze_pages(self, *, at_step: int, pages: int,
                      hold_steps: int = 8) -> "FaultInjector":
        """At engine step ``at_step``, allocate and hold ``pages`` pages
        from the engine's allocator (as many as it will give) for
        ``hold_steps`` steps — synthetic page pressure."""
        self._squeezes.append(dict(at_step=int(at_step), pages=int(pages),
                                   hold_steps=int(hold_steps), held=None,
                                   release_at=None, calls_left=None))
        return self

    def storm(self, *, at_step: int, fire: Callable) -> "FaultInjector":
        """Run ``fire(engine)`` once when the engine reaches ``at_step``
        — e.g. a burst of tenant-A ``submit()`` calls mid-flight."""
        self._storms.append([int(at_step), fire, False])
        return self

    def refuse_connect(self, *, replica: Optional[str] = None,
                       count: Optional[int] = 1,
                       prob: Optional[float] = None) -> "FaultInjector":
        """Raise ``ConnectionRefusedError`` on connection attempts to
        ``replica`` (None = any): the next ``count`` attempts, or each
        attempt with probability ``prob`` (seeded) — a replica that died
        between scrapes, as the router's transport sees it."""
        if (count is None) == (prob is None):
            raise ValueError("pass exactly one of count= / prob=")
        self._net.append(dict(kind="refuse_connect", replica=replica,
                              count=count, prob=prob))
        return self

    def slow_replica(self, *, replica: Optional[str] = None,
                     delay_s: float = 0.05, count: Optional[int] = None,
                     prob: Optional[float] = None) -> "FaultInjector":
        """Sleep ``delay_s`` before connections to ``replica`` complete
        (a straggler host / congested NIC) — forever when neither
        ``count`` nor ``prob`` is given."""
        if count is not None and prob is not None:
            raise ValueError("pass at most one of count= / prob=")
        self._net.append(dict(kind="slow_replica", replica=replica,
                              count=count, prob=prob,
                              delay_s=float(delay_s)))
        return self

    def drop_stream(self, *, replica: Optional[str] = None,
                    after_tokens: int = 3,
                    count: Optional[int] = 1) -> "FaultInjector":
        """Raise :class:`StreamDropped` once a stream from ``replica``
        has delivered ``after_tokens`` tokens — the mid-stream death the
        re-queue path must survive. Fires on the next ``count`` streams
        (None = every stream)."""
        self._net.append(dict(kind="drop_stream", replica=replica,
                              count=count, after_tokens=int(after_tokens)))
        return self

    def wrong_token(self, *, replica: Optional[str] = None,
                    after_tokens: int = 0,
                    count: Optional[int] = None) -> "FaultInjector":
        """Corrupt tokens a replica server emits (``token ^ 1``) from
        stream index ``after_tokens`` on — the **silent correctness
        fault** no latency gauge sees and the synthetic canary exists to
        catch (a drifting quantized replica, a bad KV import, a flaky
        link flipping bits). Consulted by ``ReplicaServer(faults=...)``
        via :meth:`corrupt_token`. ``count`` bounds how many tokens are
        corrupted in total (None = every eligible token until
        :meth:`clear_network`)."""
        self._net.append(dict(kind="wrong_token", replica=replica,
                              count=count, after_tokens=int(after_tokens)))
        return self

    def clear_network(self, kind: Optional[str] = None) -> int:
        """Disarm network-level faults (all, or one ``kind``) — how a
        drill 'fixes' the injected fault so recovery paths (canary
        pending→firing→**resolved**) can be asserted. Returns how many
        faults were removed."""
        keep = [f for f in self._net if kind is not None and f["kind"] != kind]
        removed = len(self._net) - len(keep)
        self._net[:] = keep
        return removed

    # -- router transport hooks ---------------------------------------------

    def _net_fire(self, fault: dict) -> bool:
        if fault.get("prob") is not None:
            return self.rng.random() < fault["prob"]
        if fault.get("count") is None:
            return True
        if fault["count"] <= 0:
            return False
        fault["count"] -= 1
        return True

    def before_connect(self, replica: str):
        """Router hook, ahead of each connection attempt: scripted
        refusals raise, slow-replica faults sleep. The attempt counter is
        the network clock the log records against."""
        self._net_calls += 1
        for fault in self._net:
            if fault["replica"] is not None and fault["replica"] != replica:
                continue
            if fault["kind"] == "slow_replica" and self._net_fire(fault):
                self.log.append(
                    (self._net_calls, "slow_replica", (replica, fault["delay_s"]))
                )
                self._sleep(fault["delay_s"])
            elif fault["kind"] == "refuse_connect" and self._net_fire(fault):
                self.log.append((self._net_calls, "refuse_connect", replica))
                raise ConnectionRefusedError(
                    f"injected connection refusal to replica {replica!r}"
                )

    def corrupt_token(self, replica: str, index: int, token: int) -> int:
        """Replica-server hook, per emitted token: an armed
        ``wrong_token`` fault flips the low bit of eligible tokens. The
        stream framing stays valid — only the *content* lies, which is
        exactly the failure class passive telemetry cannot see."""
        for fault in self._net:
            if fault["kind"] != "wrong_token":
                continue
            if fault["replica"] is not None and fault["replica"] != replica:
                continue
            if index < fault["after_tokens"]:
                continue
            if fault["count"] is not None:
                if fault["count"] <= 0:
                    continue
                fault["count"] -= 1
            self.log.append((self._net_calls, "wrong_token", (replica, index)))
            return int(token) ^ 1
        return int(token)

    def on_stream_event(self, replica: str, index: int):
        """Router hook, per received stream token: an armed
        ``drop_stream`` fault raises once ``index`` reaches its
        ``after_tokens`` threshold."""
        for fault in self._net:
            if fault["kind"] != "drop_stream":
                continue
            if fault["replica"] is not None and fault["replica"] != replica:
                continue
            if index < fault["after_tokens"]:
                continue
            if fault["count"] is not None:
                if fault["count"] <= 0:
                    continue
                fault["count"] -= 1
            self.log.append((self._net_calls, "drop_stream", (replica, index)))
            raise StreamDropped(
                f"injected mid-stream drop from replica {replica!r} "
                f"after {index} tokens"
            )

    # -- engine hooks -------------------------------------------------------

    def _maybe_sleep(self, phase: str, step: int):
        for d in self._delays:
            if d["phase"] != phase or step < d["start"]:
                continue
            if d["stop"] is not None and step >= d["stop"]:
                continue
            fire = (
                step % d["every"] == 0 if d["every"] is not None
                else self.rng.random() < d["prob"]
            )
            if fire:
                self.log.append((step, f"delay_{phase}", d["delay_s"]))
                self._sleep(d["delay_s"])

    def before_decode(self, engine):
        self._maybe_sleep("decode", engine.step_count)

    def before_prefill(self, engine):
        self._maybe_sleep("prefill", engine.step_count)

    def on_step(self, engine):
        """Step boundary: fire due storms, arm/release page squeezes."""
        step = engine.step_count
        for s in self._storms:
            if not s[2] and step >= s[0]:
                s[2] = True
                self.log.append((step, "storm", s[0]))
                s[1](engine)
        alloc = getattr(engine, "_allocator", None)
        for sq in self._squeezes:
            if sq["held"] is None and sq["release_at"] is None and step >= sq["at_step"]:
                if alloc is None:
                    sq["release_at"] = step  # flat arena: nothing to squeeze
                    continue
                held = []
                for _ in range(sq["pages"]):
                    page = alloc.alloc()
                    if page is None:
                        break
                    held.append(page)
                sq["held"] = held
                sq["release_at"] = step + sq["hold_steps"]
                # engine.step_count only advances when a dispatch actually
                # runs — a squeeze that starves every slot would freeze it
                # and hold the pages forever. Bound the hold in on_step
                # invocations too (generous, so the step-paced release
                # wins whenever the engine is making progress).
                sq["calls_left"] = 4 * sq["hold_steps"] + 16
                self.log.append((step, "squeeze_pages", len(held)))
            elif sq["held"] is not None:
                if sq["calls_left"] is not None:
                    sq["calls_left"] -= 1
                if step >= sq["release_at"] or sq["calls_left"] <= 0:
                    for page in sq["held"]:
                        alloc.release(page)
                    self.log.append((step, "release_pages", len(sq["held"])))
                    sq["held"] = None

    def release_all(self, engine):
        """Return any still-held squeeze pages (test teardown)."""
        alloc = getattr(engine, "_allocator", None)
        for sq in self._squeezes:
            if sq["held"] is not None and alloc is not None:
                for page in sq["held"]:
                    alloc.release(page)
                sq["held"] = None
