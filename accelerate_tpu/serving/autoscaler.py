"""Burn-rate-actuated autoscaler daemon: the observe→decide→act loop.

PRs 9/11/15 built every mechanism this module needs — burn-rate alerts
that fire when the SLO budget is being spent (``telemetry/alerts.py``),
a fleet collector whose health state machine and merged timeline say
what the fleet is doing (``telemetry/fleet.py``), elastic router
membership (``/v1/register``/``deregister_replica``), drain-on-SIGTERM
replica processes (``replica_server.py``/``commands/serve.py``), and a
token-exact canary (``telemetry/canary.py``). Until now a human was the
actuator. This daemon closes the loop:

- **observe** — :func:`~..telemetry.capacity.extract_signals` over the
  collector's own Timeline rings (queue derivative, arrival slope,
  capacity/headroom) plus the alert manager's firing set;
- **decide** — the hysteresis'd
  :class:`~..telemetry.capacity.Recommender` (cooldown, confirmation
  streaks, min/max clamps, the scale-in overload veto). Every decision
  — including holds — appends to ``autoscale-decisions.jsonl`` with the
  full signal snapshot that justified it: the placement-decision-log
  discipline, applied to scaling;
- **act** — scale-out spawns a replica through the existing
  ``accelerate-tpu serve replica`` CLI (reading the JSON port handshake
  off its stdout), gates it behind a token-exact canary pass *before*
  ``register_replica`` admits traffic, and waits for the collector to
  mark it placeable; scale-in drains (in-flight streams finish), then
  deregisters, then reaps — with a conservation ledger from the
  router's own counters asserting no request vanished across the
  fleet-size change.

The loop measures itself: ``autoscale_reaction_s`` (burn rule firing →
first verified token out of the new replica) is stamped on each
scale-out decision, decomposed into actuation stages (``decide_lag`` →
``spawn`` → ``canary`` → ``register`` → ``placement`` — the waterfall
discipline from ``telemetry/waterfall.py``, applied to the control
loop), and published through the ``report --diff`` sentry.

Jax-free by construction (declared in ``analysis/hygiene.py``): the
daemon runs beside the router, on boxes with no accelerator stack —
the jax-paying work happens in the subprocesses it spawns.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from ..telemetry.capacity import (
    AutoscalePolicy,
    Decision,
    Recommender,
    extract_signals,
)
from ..telemetry.fleet import DOWN_STATES, DRAINING, PLACEABLE_STATES

DEFAULT_GOLDEN = {"prompt": [1, 2, 3], "seed": 0, "max_new_tokens": 8}


# -- direct replica probing (the pre-registration canary gate) --------------


def direct_submit_fn(base_url: str, *, timeout_s: float = 30.0) -> Callable:
    """``submit_fn`` for a :class:`~..telemetry.canary.CanaryProber`
    aimed straight at one replica's ``/v1/submit`` — the gate probes the
    candidate *before* the router knows it exists, so a replica serving
    wrong tokens never receives real traffic."""
    import urllib.request

    base = base_url.rstrip("/")

    def submit(golden: dict, request_id) -> dict:
        t0 = time.perf_counter()
        payload = {
            "prompt": list(golden["prompt"]),
            "max_new_tokens": int(golden.get("max_new_tokens") or 16),
            "seed": int(golden.get("seed") or 0),
            "tenant": str(golden.get("tenant") or "_autoscale_canary"),
            "request_id": request_id,
            "stream": False,
        }
        req = urllib.request.Request(
            base + "/v1/submit", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            done = json.loads(resp.read().decode("utf-8", "replace"))
        return {
            "tokens": [int(t) for t in (done.get("tokens") or [])],
            "replica": done.get("replica"),
            "outcome": done.get("outcome"),
            "shed_reason": done.get("shed_reason"),
            "e2e_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }

    return submit


# -- spawning ---------------------------------------------------------------


class SpawnedReplica:
    """Uniform handle over one replica the autoscaler owns — subprocess
    (``proc``) or embedder-provided (``server`` with the ReplicaServer
    surface). ``drain()`` starts a graceful drain, ``wait()`` blocks for
    exit, ``kill()`` is the hard stop for a failed canary gate."""

    def __init__(self, name: str, url: str, *, proc=None, server=None):
        self.name = name
        self.url = url
        self.proc = proc
        self.server = server

    def drain(self):
        if self.proc is not None:
            import signal

            try:
                self.proc.send_signal(signal.SIGTERM)  # handler drains
            except (ProcessLookupError, OSError):
                pass
        elif self.server is not None:
            self.server.request_drain()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout_s)
                return True
            except subprocess.TimeoutExpired:
                return False
        if self.server is not None:
            return bool(self.server.serve_until_drained(timeout_s))
        return True

    def kill(self):
        if self.proc is not None:
            try:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
        elif self.server is not None:
            self.server.kill()

    @property
    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return self.server is not None


class SubprocessSpawner:
    """Spawn replicas via the existing ``accelerate-tpu serve replica``
    CLI — the same launch path the multi-process drills use — and read
    the ``{"role": "replica", "url": ...}`` JSON handshake the replica
    prints on stdout once its port is bound and its engine is warm."""

    def __init__(self, *, replica_args=("--config", "tiny"),
                 startup_timeout_s: float = 120.0, env: Optional[dict] = None,
                 python: Optional[str] = None):
        self.replica_args = tuple(str(a) for a in replica_args)
        self.startup_timeout_s = float(startup_timeout_s)
        self.env = env
        self.python = python or sys.executable

    def command(self, name: str) -> list:
        return [
            self.python, "-m", "accelerate_tpu.commands.accelerate_cli",
            "serve", "replica", "--port", "0", "--name", name,
            *self.replica_args,
        ]

    def spawn(self, name: str) -> SpawnedReplica:
        proc = subprocess.Popen(
            self.command(name), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=self.env, text=True,
        )
        try:
            handshake = self._read_handshake(proc)
        except Exception:
            try:
                proc.kill()
                proc.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
            raise
        return SpawnedReplica(name, str(handshake["url"]), proc=proc)

    def _read_handshake(self, proc) -> dict:
        """First JSON line with a ``url`` off the child's stdout (jax
        chatter and warnings may precede it); a child that exits or goes
        silent past the startup timeout is a spawn failure."""
        import queue

        q: "queue.Queue" = queue.Queue()

        def reader():
            try:
                for line in proc.stdout:
                    q.put(line)
            except (OSError, ValueError):
                pass
            q.put(None)  # EOF sentinel

        threading.Thread(
            target=reader, name="att-autoscale-handshake", daemon=True
        ).start()
        deadline = time.time() + self.startup_timeout_s
        while time.time() < deadline:
            try:
                line = q.get(timeout=0.25)
            except queue.Empty:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"replica exited rc={proc.returncode} before handshake"
                    )
                continue
            if line is None:
                raise RuntimeError(
                    f"replica stdout closed before handshake "
                    f"(rc={proc.poll()})"
                )
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and obj.get("url"):
                return obj
        raise TimeoutError(
            f"no replica handshake within {self.startup_timeout_s:.0f}s"
        )


# -- the daemon -------------------------------------------------------------


class Autoscaler:
    """One evaluate→actuate loop over a live :class:`~.router.Router`.

    ``spawn_fn(name) -> SpawnedReplica``-compatible handle overrides the
    default :class:`SubprocessSpawner` (benches and embedders pass a
    closure that builds an in-process ``ReplicaServer``). ``goldens``
    seeds the canary gate; with none given it borrows the router
    canary's recorded goldens when available, else the default golden
    in record-then-verify mode (the first gated replica records the
    truth every later one must reproduce — sound because the drills
    launch every replica from the same config + ``--init-seed``).

    Drive it deterministically with :meth:`evaluate_once` (what the
    tier-1 drill and the units do) or on a cadence with :meth:`start`.
    """

    def __init__(self, router, *, policy: Optional[AutoscalePolicy] = None,
                 spawner: Optional[SubprocessSpawner] = None,
                 spawn_fn: Optional[Callable] = None,
                 goldens: Optional[list] = None, canary_probes: int = 2,
                 log_dir: Optional[str] = None, interval_s: float = 1.0,
                 name_prefix: str = "auto",
                 placeable_timeout_s: float = 15.0,
                 drain_timeout_s: float = 30.0,
                 probe_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.time):
        self.router = router
        self.policy = policy or AutoscalePolicy()
        self.recommender = Recommender(self.policy, clock=clock)
        self._spawner = spawner
        self._spawn_fn = spawn_fn
        self.goldens = [dict(g) for g in (goldens or [])]
        self.canary_probes = max(1, int(canary_probes))
        self.interval_s = float(interval_s)
        self.name_prefix = str(name_prefix)
        self.placeable_timeout_s = float(placeable_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self.owned: dict = {}          # name -> SpawnedReplica handle
        self.decisions: list = []      # bounded ring of decision records
        self.evals = 0
        self.scale_outs = 0
        self.scale_ins = 0
        self.holds = 0
        self.canary_failures = 0
        self.spawn_failures = 0
        self.last_reaction_s: Optional[float] = None
        self._fh = None
        if log_dir:
            from ..telemetry.artifacts import ArtifactWriter

            self._fh = ArtifactWriter(
                os.path.join(log_dir, "autoscale-decisions.jsonl")
            )

    # -- observe -------------------------------------------------------------

    def fleet_size(self) -> int:
        """Replicas that count against min/max: everything not down and
        not draining — a ``starting`` spawn in its canary gate already
        holds a slot, or the loop would double-spawn while it warms."""
        collector = self.router.collector
        with collector._lock:
            return sum(
                1 for r in collector.replicas.values()
                if r.state not in DOWN_STATES and r.state != DRAINING
            )

    def _burn_fired_t(self, alert_states: dict, now: float) -> float:
        """When the justifying burn rule started firing — the reaction
        clock's zero."""
        fired = [
            st.get("since") for name, st in alert_states.items()
            if name in self.policy.burn_rules
            and st.get("state") == "firing"
            and isinstance(st.get("since"), (int, float))
        ]
        return min(fired) if fired else now

    # -- decide + act --------------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> dict:
        """One loop turn: signals → decision → (maybe) actuation.
        Returns the logged decision record."""
        now = self._clock() if now is None else float(now)
        collector = self.router.collector
        alert_states = collector.alerts.states_snapshot()
        signals = extract_signals(
            collector.timeline, now=now,
            fast_s=self.policy.fast_s, slow_s=self.policy.slow_s,
            horizon_s=self.policy.horizon_s, alert_states=alert_states,
        )
        firing = collector.alerts.firing()
        decision = self.recommender.decide(
            signals=signals, firing=firing, replicas=self.fleet_size(),
            now=now,
        )
        with self._lock:
            self.evals += 1
        if decision.action == "scale_out":
            record = self._actuate_out(decision, alert_states)
        elif decision.action == "scale_in":
            record = self._actuate_in(decision)
        else:
            with self._lock:
                self.holds += 1
            record = decision.to_record()
            record["outcome"] = "held"
        self._log(record)
        return record

    def _next_name(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{self.name_prefix}-{self._seq}"

    def _gate_goldens(self) -> list:
        if self.goldens:
            return self.goldens
        canary = getattr(self.router, "canary", None)
        if canary is not None and getattr(canary, "goldens", None):
            self.goldens = [dict(g) for g in canary.goldens]
        else:
            self.goldens = [dict(DEFAULT_GOLDEN)]
        return self.goldens

    def _canary_gate(self, handle: SpawnedReplica) -> tuple:
        """Probe the candidate directly until every golden passed once
        (token-exact). Returns ``(passed, first_token_t, results)`` —
        the first passing probe's completion stamps the reaction
        clock."""
        from ..telemetry.canary import CanaryProber

        goldens = self._gate_goldens()
        prober = CanaryProber(
            direct_submit_fn(handle.url, timeout_s=self.probe_timeout_s),
            goldens, clock=self._clock,
        )
        first_token_t = None
        passed = True
        results = []
        probes = max(self.canary_probes, len(goldens))
        try:
            for _ in range(probes):
                result = prober.probe_once()
                results.append({
                    "passed": result["passed"],
                    "reason": result.get("reason"),
                    "e2e_ms": result.get("e2e_ms"),
                })
                if not result["passed"]:
                    passed = False
                    break
                if first_token_t is None:
                    first_token_t = self._clock()
        finally:
            prober.close()
        if passed:
            # keep any goldens the gate just recorded: the next spawn
            # must reproduce THIS replica's tokens, not re-record
            self.goldens = [dict(g) for g in prober.goldens]
        return passed, first_token_t, results

    def _await_placeable(self, name: str, timeout_s: float) -> bool:
        """Wait for the collector to scrape the newcomer into a
        placeable state (traffic is routable within one poll of
        registration)."""
        collector = self.router.collector
        deadline = time.time() + timeout_s
        while True:
            with collector._lock:
                r = collector.replicas.get(name)
                if r is not None and r.state in PLACEABLE_STATES:
                    return True
            if time.time() >= deadline:
                return False
            # nudge a poll if no background cadence is running
            if getattr(collector, "_sampler", None) is None:
                collector.poll_once()
            else:
                time.sleep(min(0.05, timeout_s / 20.0))

    def _actuate_out(self, decision: Decision, alert_states: dict) -> dict:
        fired_t = self._burn_fired_t(alert_states, decision.t_unix_s)
        stages = {"decide_lag_s": round(
            max(0.0, decision.t_unix_s - fired_t), 3
        )}
        record = decision.to_record()
        name = self._next_name()
        record["replica"] = name
        t0 = self._clock()
        try:
            if self._spawn_fn is not None:
                handle = self._spawn_fn(name)
            else:
                if self._spawner is None:
                    self._spawner = SubprocessSpawner()
                handle = self._spawner.spawn(name)
        except Exception as e:
            with self._lock:
                self.spawn_failures += 1
            record["outcome"] = "spawn_failed"
            record["error"] = f"{type(e).__name__}: {e}"
            record["stages"] = stages
            return record
        stages["spawn_s"] = round(self._clock() - t0, 3)

        t1 = self._clock()
        passed, first_token_t, probes = self._canary_gate(handle)
        stages["canary_s"] = round(self._clock() - t1, 3)
        record["canary"] = probes
        if not passed:
            # the gate is the whole point: wrong tokens never serve
            handle.kill()
            with self._lock:
                self.canary_failures += 1
            record["outcome"] = "canary_failed"
            record["stages"] = stages
            return record

        t2 = self._clock()
        self.router.register_replica(name, handle.url)
        stages["register_s"] = round(self._clock() - t2, 3)
        t3 = self._clock()
        placed = self._await_placeable(name, self.placeable_timeout_s)
        stages["placement_s"] = round(self._clock() - t3, 3)
        with self._lock:
            self.owned[name] = handle
            self.scale_outs += 1
            reaction = (
                round(first_token_t - fired_t, 3)
                if first_token_t is not None else None
            )
            self.last_reaction_s = reaction
        record["outcome"] = "scaled_out" if placed else "registered_not_placed"
        record["url"] = handle.url
        record["stages"] = stages
        if reaction is not None:
            record["autoscale_reaction_s"] = reaction
            record["burn_fired_unix_s"] = round(fired_t, 3)
        return record

    def _pick_victim(self) -> Optional[str]:
        """Newest owned replica still registered (LIFO: the autoscaler
        only reaps processes it spawned and still holds a handle to)."""
        with self._lock:
            names = [n for n in self.owned if n in self.router._replicas]
            return names[-1] if names else None

    def _actuate_in(self, decision: Decision) -> dict:
        record = decision.to_record()
        name = self._pick_victim()
        if name is None:
            record["outcome"] = "no_owned_replica"
            return record
        record["replica"] = name
        before = self.conservation()
        handle = self.owned[name]
        stages = {}
        # drain FIRST: the draining gauge flips the replica out of
        # placement on the next scrape while in-flight streams finish —
        # deregistering before the drain would strand them re-queued
        t0 = self._clock()
        handle.drain()
        drained = handle.wait(self.drain_timeout_s)
        stages["drain_s"] = round(self._clock() - t0, 3)
        t1 = self._clock()
        self.router.deregister_replica(name)
        if not drained:
            handle.kill()
        stages["reap_s"] = round(self._clock() - t1, 3)
        with self._lock:
            self.owned.pop(name, None)
            self.scale_ins += 1
        after = self.conservation()
        record["outcome"] = "scaled_in" if drained else "reaped_after_timeout"
        record["stages"] = stages
        record["ledger"] = {
            "before": before, "after": after,
            "conserved": bool(after["conserved"]),
        }
        return record

    # -- ledger / gauges -----------------------------------------------------

    def conservation(self) -> dict:
        """The zero-lost-requests ledger from the router's own counters:
        every submitted request is accounted terminal or in flight."""
        m = self.router.metrics()
        submitted = int(m.get("router/requests_submitted") or 0)
        completed = int(m.get("router/requests_completed") or 0)
        shed = int(m.get("router/requests_shed") or 0)
        cancelled = int(m.get("router/requests_cancelled") or 0)
        inflight = int(m.get("router/inflight") or 0)
        return {
            "submitted": submitted, "completed": completed, "shed": shed,
            "cancelled": cancelled, "inflight": inflight,
            "conserved": submitted == completed + shed + cancelled + inflight,
        }

    def rollup_keys(self) -> dict:
        """``autoscale/*`` gauges for the router's ``/metrics`` (merge
        policy: counters sum, ``last_reaction_s`` is a plain gauge)."""
        with self._lock:
            out = {
                "autoscale/evals": self.evals,
                "autoscale/scale_outs": self.scale_outs,
                "autoscale/scale_ins": self.scale_ins,
                "autoscale/holds": self.holds,
                "autoscale/canary_failures": self.canary_failures,
                "autoscale/spawn_failures": self.spawn_failures,
                "autoscale/replicas_owned": len(self.owned),
            }
            if self.last_reaction_s is not None:
                out["autoscale/last_reaction_s"] = self.last_reaction_s
        return out

    def _log(self, record: dict):
        with self._lock:
            self.decisions.append(record)
            if len(self.decisions) > 512:
                del self.decisions[: len(self.decisions) - 512]
            fh = self._fh
        if fh is not None:
            fh.write_line(json.dumps(record))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="att-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:
                pass  # the loop must survive one bad evaluation

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self, reap: bool = True):
        """Stop the loop; with ``reap`` (default) drain and reap every
        replica the daemon still owns — an exiting autoscaler must not
        leak subprocesses."""
        self.stop()
        if reap:
            with self._lock:
                owned = list(self.owned.items())
            for name, handle in owned:
                try:
                    handle.drain()
                    if not handle.wait(self.drain_timeout_s):
                        handle.kill()
                except Exception:
                    handle.kill()
                try:
                    self.router.deregister_replica(name)
                except Exception:
                    pass
                with self._lock:
                    self.owned.pop(name, None)
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def load_autoscale_decisions(target: str) -> list:
    """Offline read of ``autoscale-decisions.jsonl`` under a telemetry
    dir — what ``report`` renders and the troubleshooting runbook reads
    against the timeline."""
    from ..telemetry.artifacts import artifact_files, iter_jsonl

    paths = (artifact_files(target, "autoscale-decisions.jsonl")
             if os.path.isdir(target) else artifact_files(target))
    return [rec for rec in iter_jsonl(paths) if rec.get("action")]
