"""One serving replica as an HTTP process: the engine behind a wire.

:class:`ReplicaServer` wraps a live :class:`~.engine.ServingEngine` in a
stdlib-HTTP JSONL surface — the unit the router (``serving/router.py``)
places onto, fails over between, and scales elastically:

- ``POST /v1/submit`` — queue one request; with ``"stream": true`` the
  response is JSONL (``{"event": "token", ...}`` per emitted token, one
  terminal ``{"event": "done", ...}``), else a single JSON document. A
  connection that closes *without* the terminal event is the replica-
  death signature the router re-queues on.
- ``POST /v1/cancel`` — ``{request_id}``; the engine frees the slot and
  pages at its next iteration (the PR 7 cancel path).
- ``POST /v1/kv/export`` / ``POST /v1/kv/import`` — the KV handoff: a
  finished prompt's prefix-cache pages ship VERBATIM (quantized
  payload+scales pages, the PR 10 wire format) so prefill replicas hand
  finished KV to decode replicas and a migrated session keeps its warm
  cache. Import installs through a warmup-compiled program: zero
  recompiles on the receiving replica.
- ``GET /metrics`` — the standard Prometheus scrape (the engine's
  telemetry session when attached, else a minimal engine-gauges shim),
  which is exactly what the router's ``FleetCollector`` polls for
  health + placement.
- ``GET /v1/health`` — a one-shot JSON health/identity document.
- ``POST /v1/flight`` — remote-triggered flight-recorder dump
  (``{reason}``): how the canary prober captures the degraded
  replica's debug bundle while the fault is still live.

Lifecycle: ``start()`` runs the engine's scheduler loop on a background
thread (all device dispatches stay on that one thread; the KV endpoints
serialize against it with one lock). SIGTERM — with
``handle_signals=True`` — triggers the PR 7 drain choreography:
``request_drain()`` (flag-only, signal-safe), in-flight requests finish
and their streams complete, the flight recorder dumps, the process
exits cleanly. A *draining* replica still answers ``/metrics`` (the
``serving/draining`` gauge is how the fleet health machine sees it) and
still serves its in-flight streams; new submits shed with
``shed_reason="draining"``.

This module is jax-free at import (declared in ``analysis/hygiene.py``):
it receives a built engine and never imports the engine module itself —
a supervisor/CLI tier can import it to parse flags before paying jax.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ..telemetry.exporter import prometheus_text


class _EngineMetricsSession:
    """Minimal scrape shim for an engine with no telemetry session:
    ``prometheus_text`` needs ``rollup()``/``hists``/``alerts`` and a
    freshness clock. Freshness tracks the engine loop's last iteration
    (``_touch``), so a wedged loop still reads as a degrading replica."""

    def __init__(self, engine):
        self.engine = engine
        self.hists: dict = {}
        self.alerts = None
        self.last_sample_unix_s = time.time()

    def _touch(self):
        self.last_sample_unix_s = time.time()

    def rollup(self) -> dict:
        return self.engine.metrics()


class ReplicaServer:
    """HTTP wrapper around one live engine. ``name`` becomes the
    engine's ``replica`` identity (stamped into every request record —
    the trace-stitching key). ``port=0`` binds an ephemeral port; read
    the resolved one from ``.port``."""

    def __init__(self, engine, *, host: str = "127.0.0.1", port: int = 0,
                 name: Optional[str] = None, handle_signals: bool = False,
                 faults=None):
        import http.server

        self.engine = engine
        # replica-side fault injection (wrong-token corruption drills):
        # consulted per emitted token via corrupt_token()
        self._faults = faults
        if name:
            engine.replica = str(name)
        self.name = engine.replica or f"replica@{port}"
        self._session = (
            engine.telemetry if engine.telemetry is not None
            else _EngineMetricsSession(engine)
        )
        self._stop = False
        self._dead = False          # hard-fail switch (kill drills)
        self._drained = threading.Event()
        self._engine_lock = threading.Lock()   # loop thread vs KV endpoints
        self._live_lock = threading.Lock()
        self._live: dict = {}       # str(request_id) -> Request
        self._loop_thread: Optional[threading.Thread] = None
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            timeout = 30.0

            def do_GET(self):  # noqa: N802 (stdlib casing)
                server._get(self)

            def do_POST(self):  # noqa: N802
                server._post(self)

            def log_message(self, *args):
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = self.httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name=f"att-replica-http-{self.name}", daemon=True,
        )
        if handle_signals:
            self._install_signal_handler()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaServer":
        """Serve: HTTP thread + the engine scheduler loop thread."""
        self._http_thread.start()
        if self._loop_thread is None:
            self._loop_thread = threading.Thread(
                target=self._loop, name=f"att-replica-loop-{self.name}",
                daemon=True,
            )
            self._loop_thread.start()
        return self

    def _loop(self):
        shim = self._session if isinstance(
            self._session, _EngineMetricsSession
        ) else None
        while not self._stop:
            with self._engine_lock:
                busy = self.engine.step()
            if shim is not None:
                shim._touch()
            if self.engine._draining and not self.engine._pending():
                # drain complete: every request reached its outcome and
                # every stream's terminal event is writable — record the
                # flight bundle and let serve_until_drained() return
                self.engine._flight_dump("replica_drain_complete")
                self._drained.set()
                return
            if not busy:
                time.sleep(0.001)

    def serve_until_drained(self, timeout_s: Optional[float] = None) -> bool:
        """Block until a drain completes (the SIGTERM path's main-thread
        wait). True when drained; False on timeout/stop."""
        return self._drained.wait(timeout_s)

    def request_drain(self):
        """Stop admitting, finish in-flight, then the loop thread stops.
        Safe from a signal handler (flag-only, like the engine's)."""
        self.engine.request_drain()

    def _install_signal_handler(self):
        import signal

        def on_sigterm(signum, frame):
            self.request_drain()

        try:
            signal.signal(signal.SIGTERM, on_sigterm)
        except ValueError:
            pass  # not the main thread: the embedder owns signals

    def close(self, drain_timeout_s: float = 5.0):
        """Graceful stop: drain, wait for in-flight to finish, shut the
        HTTP server down."""
        if not self._dead:
            self.engine.request_drain()
            self._drained.wait(drain_timeout_s)
        self._stop = True
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)
            self._loop_thread = None
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except OSError:
            pass
        if self._http_thread.is_alive():
            self._http_thread.join(timeout=5.0)

    def kill(self):
        """Hard-fail NOW (the in-process stand-in for SIGKILL in kill
        drills): the scheduler loop stops mid-whatever, every in-flight
        stream breaks off without its terminal event, the listener
        closes. No drain, no flight record — exactly what a dead process
        looks like from the router's side."""
        self._dead = True
        self._stop = True
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except OSError:
            pass

    # -- handlers (each on its own daemon thread) ---------------------------

    @staticmethod
    def _read_json(handler) -> dict:
        n = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(n) if n else b"{}"
        return json.loads(body or b"{}")

    @staticmethod
    def _send_json(handler, payload, status: int = 200):
        body = json.dumps(payload).encode()
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _get(self, handler):
        if self._dead:
            return  # connection drops — a dead process answers nothing
        if handler.path in ("/metrics", "/"):
            body = prometheus_text(self._session).encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif handler.path == "/v1/health":
            m = self.engine.metrics()
            self._send_json(handler, {
                "replica": self.name,
                "draining": bool(m.get("serving/draining")),
                "load_score": m.get("serving/load_score"),
                "queue_depth": m.get("serving/queue_depth"),
                "free_slots": m.get("serving/free_slots"),
            })
        elif handler.path == "/v1/kv/directory":
            # the peer-tier contract: advertise which prefixes this
            # replica can export, so a peer's miss becomes a pull
            # instead of a cold prefill (docs/serving.md)
            with self._engine_lock:
                directory = self.engine.kv_directory()
            self._send_json(handler, directory)
        else:
            handler.send_error(404)

    def _post(self, handler):
        if self._dead:
            return
        try:
            body = self._read_json(handler)
        except ValueError:
            handler.send_error(400, "bad json")
            return
        if handler.path == "/v1/submit":
            self._handle_submit(handler, body)
        elif handler.path == "/v1/cancel":
            self._handle_cancel(handler, body)
        elif handler.path == "/v1/kv/export":
            self._handle_kv_export(handler, body)
        elif handler.path == "/v1/kv/import":
            self._handle_kv_import(handler, body)
        elif handler.path == "/v1/flight":
            self._handle_flight(handler, body)
        else:
            handler.send_error(404)

    def _handle_flight(self, handler, body: dict):
        """Remote-triggered flight dump: the canary prober (or an
        operator's curl) captures THIS replica's debug bundle while a
        fault is live — the bundle names in-flight requests, recent
        gauges, and the engine's last decisions."""
        reason = str(body.get("reason") or "remote_request")[:64]
        try:
            dumped = bool(self.engine.flight_dump(reason))
        except Exception:
            dumped = False
        self._send_json(handler, {"ok": dumped, "replica": self.name,
                                  "reason": reason})

    # -- submit / stream ----------------------------------------------------

    def _handle_submit(self, handler, body: dict):
        prompt = body.get("prompt") or []
        if not prompt:
            handler.send_error(400, "empty prompt")
            return
        try:
            req = self.engine.submit(
                [int(t) for t in prompt],
                max_new_tokens=int(body.get("max_new_tokens") or 32),
                seed=int(body.get("seed") or 0),
                tenant=str(body.get("tenant") or "default"),
                priority=int(body.get("priority") or 0),
                timeout_s=body.get("timeout_s"),
                request_id=body.get("request_id"),
            )
        except ValueError as e:
            handler.send_error(400, str(e)[:200])
            return
        rid = str(req.id)
        with self._live_lock:
            self._live[rid] = req
        try:
            if body.get("stream", True):
                self._stream_request(handler, req)
            else:
                self._await_request(handler, req)
        finally:
            with self._live_lock:
                self._live.pop(rid, None)

    def _done_event(self, req) -> dict:
        return {
            "event": "done", "request_id": req.id, "replica": self.name,
            "outcome": req.outcome, "finish_reason": req.finish_reason,
            "shed_reason": req.shed_reason,
            "tokens": [int(t) for t in req.tokens],
            "prefix_hit": int(req.prefix_hit),
        }

    def _stream_request(self, handler, req):
        """JSONL token stream. Reads ``req.tokens`` incrementally off
        the handler thread (list append is atomic; the engine loop owns
        the writes) — no callback into the engine, so a slow client can
        never stall the scheduler loop. A hard-failed server breaks the
        stream off with no terminal event — the router's re-queue
        trigger."""
        handler.send_response(200)
        handler.send_header("Content-Type", "application/jsonl")
        handler.end_headers()
        sent = 0
        try:
            while True:
                if self._dead:
                    return  # mid-stream drop: connection closes, no "done"
                n = len(req.tokens)
                while sent < n:
                    token = int(req.tokens[sent])
                    if self._faults is not None:
                        # wrong-token drill: the engine computed the right
                        # answer, the wire lies — canary territory
                        token = int(self._faults.corrupt_token(
                            self.name, sent, token
                        ))
                    line = json.dumps({
                        "event": "token", "i": sent, "token": token,
                        "request_id": req.id, "replica": self.name,
                    })
                    handler.wfile.write((line + "\n").encode())
                    sent += 1
                handler.wfile.flush()
                if req.done and sent >= len(req.tokens):
                    handler.wfile.write(
                        (json.dumps(self._done_event(req)) + "\n").encode()
                    )
                    handler.wfile.flush()
                    return
                time.sleep(0.002)
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client (or router hop) went away: free the slot now
            req.cancel()

    def _await_request(self, handler, req):
        while not req.done:
            if self._dead:
                return
            time.sleep(0.002)
        self._send_json(handler, self._done_event(req))

    def _handle_cancel(self, handler, body: dict):
        rid = str(body.get("request_id"))
        with self._live_lock:
            req = self._live.get(rid)
        if req is None:
            self._send_json(handler, {"ok": False, "error": "unknown request"},
                            status=404)
            return
        self._send_json(handler, {"ok": req.cancel()})

    # -- KV handoff ---------------------------------------------------------

    def _handle_kv_export(self, handler, body: dict):
        tokens = body.get("tokens") or []
        try:
            with self._engine_lock:
                handoff = self.engine.export_prefix_kv(
                    [int(t) for t in tokens]
                )
        except ValueError as e:
            handler.send_error(409, str(e)[:200])
            return
        if handoff is None:
            self._send_json(handler, {"error": "prefix not cached"},
                            status=404)
            return
        self._send_json(handler, handoff)

    def _handle_kv_import(self, handler, body: dict):
        try:
            with self._engine_lock:
                installed = self.engine.import_prefix_kv(body)
        except ValueError as e:
            handler.send_error(409, str(e)[:200])
            return
        self._send_json(handler, {"installed_tokens": int(installed),
                                  "replica": self.name})
