"""Deterministic workload replay plane: the seeded load generator.

"Millions of users" claims are worthless without replayable ground truth
(ROADMAP item 1). This module generates *traffic* the way the rest of
the repo generates *programs*: seeded, deterministic, and replayable —
``build_schedule(spec)`` is a pure function of a :class:`WorkloadSpec`,
so the same seed yields a byte-identical request schedule
(:func:`schedule_digest` is the witness) on any host, any day, with no
wall-clock dependence. Runs target three tiers with one driver API:

- a bare :class:`~.engine.ServingEngine` (in-process, single-threaded —
  the tier-1 drill path),
- a :class:`~.replica_server.ReplicaServer` **URL** (stdlib-HTTP/JSONL,
  one thread per in-flight request),
- the :class:`~.router.Router` front door (synchronous ``submit``, so
  concurrency is caller threads — same as the failover drills).

Two driver shapes:

- **open loop** arrivals ignore completions: Poisson (``expovariate``
  gaps at ``rate_rps``), bursty (``burst_size`` simultaneous arrivals
  per gap), a **ramp** (rate interpolates linearly across the run — the
  saturation sweep's single-run cousin), or **diurnal** (a sinusoid over
  ``period_s`` modulating any of the other processes — the autoscaler
  drill's traffic shape: load that swells past capacity and recedes).
- **closed loop**: ``users`` concurrent users, each submitting its next
  request only after the previous finished plus a drawn think time —
  the arrival rate self-regulates to the service rate, which is what
  makes conservation drills terminate.

Multi-tenant mixes draw each request group's tenant by weight, with
per-tenant prompt/output length distributions, and *session* groups
model multi-turn conversations whose turn ``k`` prompt is turn ``k-1``'s
prompt plus fresh tokens — growing shared prefixes, the exact shape that
exercises the ``PrefixCache``, router session affinity, and KV handoff.

The run returns (and optionally writes, ``loadtest-offered.json``) the
**offered-load record**: one entry per scheduled request with outcome,
client-observed TTFT/ITL/E2E, and the schedule digest —
``telemetry/scorecard.py`` joins it with the server-side artifacts into
the SLO scorecard. ``instrument=False`` drops the per-token callbacks
and timing capture (the ≥0.7x zero-overhead witness baseline).

Jax-free by contract (declared in ``analysis/hygiene.py``, locked by
tests/test_imports.py): CI drills, the bench, and a TPU pod's load box
all replay the same spec from machines with no accelerator stack.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import math
import random
import threading
import time
import urllib.parse
from dataclasses import asdict, dataclass, field
from typing import Optional

import numpy as np

from .faults import FaultInjector

# -- workload spec ----------------------------------------------------------

#: JSON-friendly length/time distributions: ``{"fixed": 8}``,
#: ``{"uniform": [lo, hi]}`` (inclusive ints), ``{"choice": [a, b, c]}``.
def _draw(rng: random.Random, dist, lo: int = 1) -> float:
    if isinstance(dist, (int, float)):
        return dist
    if "fixed" in dist:
        return dist["fixed"]
    if "uniform" in dist:
        a, b = dist["uniform"]
        if isinstance(a, float) or isinstance(b, float):
            return rng.uniform(a, b)
        return rng.randint(int(a), int(b))
    if "choice" in dist:
        return rng.choice(list(dist["choice"]))
    raise ValueError(f"unknown distribution {dist!r}")


def _draw_len(rng: random.Random, dist, lo: int = 1) -> int:
    return max(lo, int(_draw(rng, dist, lo)))


@dataclass
class TenantSpec:
    """One tenant's slice of the traffic mix."""

    name: str
    weight: float = 1.0
    priority: int = 0
    prompt_len: dict = field(default_factory=lambda: {"uniform": [8, 24]})
    max_new_tokens: dict = field(default_factory=lambda: {"fixed": 8})
    #: probability a request group is a multi-turn session
    session_prob: float = 0.0
    session_turns: dict = field(default_factory=lambda: {"uniform": [2, 4]})
    #: tokens appended to the shared prefix per follow-up turn
    turn_growth: dict = field(default_factory=lambda: {"uniform": [4, 12]})
    #: open loop: gap between a session's turns; closed loop: think time
    #: before each follow-up request
    think_time_s: dict = field(default_factory=lambda: {"fixed": 0.0})


@dataclass
class WorkloadSpec:
    """The replayable workload description (JSON round-trippable — the
    format CI drills, the bench, and ``accelerate-tpu loadtest`` share;
    docs/serving.md "Load testing & the SLO scorecard" documents it)."""

    name: str = "workload"
    seed: int = 0
    mode: str = "open"                 # open | closed
    num_requests: int = 64
    #: open loop: {"process": "poisson"|"burst"|"ramp"|"diurnal",
    #: "rate_rps": r, "burst_size": k, "rate_rps_to": r2}; diurnal
    #: modulates a "base" process ("poisson"|"burst"|"ramp", default
    #: poisson) by 1 + amplitude*sin(2*pi*t/period_s), with
    #: "period_s" (default 60) and "amplitude" in [0, 1) (default 0.5)
    arrival: dict = field(default_factory=lambda: {
        "process": "poisson", "rate_rps": 32.0,
    })
    users: int = 4                     # closed loop concurrency
    vocab_size: int = 256
    #: cap on any generated prompt length (sessions stop growing here);
    #: keep <= target max_cache_len - max_new_tokens
    prompt_cap: int = 96
    tenants: list = field(default_factory=lambda: [TenantSpec("default")])
    #: SLO targets the scorecard grades against (overridable per run)
    slo: dict = field(default_factory=lambda: {
        "ttft_ms": 1000.0, "itl_ms": 100.0,
    })

    def __post_init__(self):
        if self.mode not in ("open", "closed"):
            raise ValueError(f"mode must be open|closed, got {self.mode!r}")
        self.tenants = [
            t if isinstance(t, TenantSpec) else TenantSpec(**t)
            for t in self.tenants
        ]
        if not self.tenants:
            raise ValueError("workload needs at least one tenant")

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "WorkloadSpec":
        return cls(**doc)

    @classmethod
    def load(cls, path: str) -> "WorkloadSpec":
        with open(path) as f:
            return cls.from_json(json.load(f))

    def save(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")


# -- schedule generation (pure function of the spec) ------------------------


@dataclass
class ScheduledRequest:
    index: int            # position in the final (time-sorted) schedule
    at_s: float           # open loop: arrival offset from run start
    user: int             # closed loop: issuing user
    tenant: str
    priority: int
    session: Optional[str]
    turn: int
    think_s: float        # closed loop: pause before this request
    prompt: np.ndarray    # int32 token ids
    max_new_tokens: int
    seed: int             # per-request decode seed

    @property
    def request_id(self) -> str:
        return f"lg{self.seed & 0xffff:04x}-{self.index}"


def _arrival_gaps(rng: random.Random, arrival: dict, i: int, n: int,
                  t: float = 0.0) -> float:
    """Gap before arrival-group ``i`` of ``n`` under the arrival spec.
    ``t`` is the schedule clock so far (schedule time, not wall time —
    determinism holds); only ``diurnal`` reads it."""
    process = arrival.get("process", "poisson")
    rate = float(arrival.get("rate_rps", 32.0))
    if process == "poisson":
        return rng.expovariate(rate)
    if process == "burst":
        k = max(1, int(arrival.get("burst_size", 4)))
        # k groups arrive together, then the gap that keeps the mean rate
        return rng.expovariate(rate / k) if i % k == 0 else 0.0
    if process == "ramp":
        r2 = float(arrival.get("rate_rps_to", rate * 4))
        frac = i / max(1, n - 1)
        return rng.expovariate(rate + (r2 - rate) * frac)
    if process == "diurnal":
        # sinusoidal rate modulation composed with a base process: the
        # base draws its gap (identical rng consumption → composable
        # determinism), then the gap stretches/compresses by the local
        # rate multiplier at schedule time t
        base = dict(arrival)
        base["process"] = str(arrival.get("base", "poisson"))
        if base["process"] == "diurnal":
            raise ValueError("diurnal cannot compose with itself")
        period = max(1e-6, float(arrival.get("period_s", 60.0)))
        amp = min(0.99, max(0.0, float(arrival.get("amplitude", 0.5))))
        mod = 1.0 + amp * math.sin(2.0 * math.pi * t / period)
        return _arrival_gaps(rng, base, i, n) / max(1e-3, mod)
    raise ValueError(f"unknown arrival process {process!r}")


def build_schedule(spec: WorkloadSpec) -> list:
    """The full request schedule — a pure function of the spec: one
    ``random.Random(spec.seed)`` drives every draw in a fixed order, so
    the same seed is byte-identical (:func:`schedule_digest`) across
    runs, hosts, and targets. No wall clock anywhere."""
    rng = random.Random(spec.seed)
    weights = [max(0.0, float(t.weight)) for t in spec.tenants]
    out: list = []
    t_clock = 0.0
    group = 0
    user = 0
    while len(out) < spec.num_requests:
        t_clock += _arrival_gaps(rng, spec.arrival, group, spec.num_requests,
                                 t=t_clock)
        tenant = rng.choices(spec.tenants, weights=weights)[0]
        turns = 1
        session = None
        if tenant.session_prob > 0 and rng.random() < tenant.session_prob:
            turns = _draw_len(rng, tenant.session_turns, lo=1)
            session = f"s{spec.seed}-{group}"
        prompt = np.asarray(
            [rng.randrange(3, spec.vocab_size) for _ in
             range(_draw_len(rng, tenant.prompt_len))],
            np.int32,
        )
        at = t_clock
        for turn in range(turns):
            think = 0.0
            if turn:
                grow = _draw_len(rng, tenant.turn_growth)
                if prompt.size < spec.prompt_cap:
                    fresh = [rng.randrange(3, spec.vocab_size)
                             for _ in range(grow)]
                    prompt = np.concatenate(
                        [prompt, np.asarray(fresh, np.int32)]
                    )
                think = max(0.0, float(_draw(rng, tenant.think_time_s)))
                at += think
            prompt = prompt[: spec.prompt_cap]
            out.append(ScheduledRequest(
                index=-1, at_s=round(at, 9), user=user, tenant=tenant.name,
                priority=int(tenant.priority), session=session, turn=turn,
                think_s=round(think, 9), prompt=prompt.copy(),
                max_new_tokens=_draw_len(rng, tenant.max_new_tokens),
                seed=rng.randrange(1 << 31),
            ))
        group += 1
        user = (user + 1) % max(1, int(spec.users))
    out = out[: spec.num_requests]
    if spec.mode == "open":
        # stable sort: a session's turns keep their order at equal times
        out.sort(key=lambda s: s.at_s)
    for i, s in enumerate(out):
        s.index = i
    return out


def schedule_digest(schedule: list) -> str:
    """Canonical digest of a schedule — the byte-identity witness the
    determinism tests (and ``loadtest replay``) compare."""
    h = hashlib.blake2b(digest_size=16)
    for s in schedule:
        h.update((
            f"{s.index}|{s.at_s:.9f}|{s.user}|{s.tenant}|{s.priority}|"
            f"{s.session}|{s.turn}|{s.think_s:.9f}|{s.max_new_tokens}|"
            f"{s.seed}|"
        ).encode())
        h.update(np.ascontiguousarray(s.prompt, np.int32).tobytes())
    return h.hexdigest()


def paired_drill(seed: int, spec: WorkloadSpec):
    """One seed pair -> (workload, fault injector): a fault drill and
    its traffic reproduce together (satellite of the replay plane — the
    storm drills in tests/test_ops_plane.py ride this instead of
    hand-rolled submit loops)."""
    import dataclasses

    return (
        dataclasses.replace(spec, seed=int(seed)),
        FaultInjector(seed=int(seed)),
    )


def submit_burst(engine, spec: WorkloadSpec) -> list:
    """Submit a spec's entire schedule into a bare engine immediately
    (arrival offsets ignored) and return the live request handles — the
    storm-drill ``fire=`` helper: deterministic burst traffic from the
    same seed that drives the :class:`~.faults.FaultInjector`."""
    return [
        engine.submit(
            s.prompt, max_new_tokens=s.max_new_tokens, seed=s.seed,
            tenant=s.tenant, priority=s.priority, request_id=s.request_id,
        )
        for s in build_schedule(spec)
    ]


# -- offered-load record ----------------------------------------------------


@dataclass
class LoadgenResult:
    """What one run offered and what came back — the scorecard's primary
    input. ``records``: one JSON-safe dict per scheduled request."""

    spec: dict
    records: list
    wall_s: float
    digest: str
    target: str = "engine"

    def counts(self) -> dict:
        c = {"offered": len(self.records), "finished": 0, "shed": 0,
             "cancelled": 0, "in_flight": 0, "tokens_out": 0}
        for r in self.records:
            out = r.get("outcome")
            if out in ("finished", "shed", "cancelled"):
                c[out] += 1
            else:
                c["in_flight"] += 1
            c["tokens_out"] += int(r.get("tokens_out") or 0)
        return c

    @property
    def tokens_per_s(self) -> float:
        return self.counts()["tokens_out"] / self.wall_s if self.wall_s > 1e-9 else 0.0

    def to_json(self) -> dict:
        return {"spec": self.spec, "records": self.records,
                "wall_s": self.wall_s, "digest": self.digest,
                "target": self.target}

    def write(self, out_dir: str) -> str:
        import os

        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "loadtest-offered.json")
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")
        return path


def load_offered(target: str) -> Optional[LoadgenResult]:
    """Read ``loadtest-offered.json`` from a file or artifact dir."""
    import os

    path = target
    if os.path.isdir(target):
        path = os.path.join(target, "loadtest-offered.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return LoadgenResult(
        spec=doc.get("spec") or {}, records=doc.get("records") or [],
        wall_s=float(doc.get("wall_s") or 0.0),
        digest=doc.get("digest") or "", target=doc.get("target") or "?",
    )


class _Capture:
    """Per-request client-side observation (token timestamps when
    instrumented; outcome mapping either way)."""

    __slots__ = ("sched", "submit_t", "token_t", "handle")

    def __init__(self, sched: ScheduledRequest):
        self.sched = sched
        self.submit_t: float = 0.0
        self.token_t: list = []
        self.handle = None

    def on_token(self, _tok, _req=None):
        self.token_t.append(time.monotonic())

    def record(self, t0: float, *, outcome, finish_reason=None,
               shed_reason=None, tokens_out=0, replica=None,
               first_token_t=None, finish_t=None,
               instrument=True) -> dict:
        s = self.sched
        rec = {
            "index": s.index, "request_id": s.request_id,
            "tenant": s.tenant, "session": s.session, "turn": s.turn,
            "prompt_len": int(s.prompt.size),
            "max_new_tokens": s.max_new_tokens,
            "offered_t_s": s.at_s,
            "outcome": outcome, "finish_reason": finish_reason,
            "shed_reason": shed_reason, "tokens_out": int(tokens_out),
            "replica": replica,
        }
        if not instrument:
            return rec
        rec["submit_t_s"] = round(self.submit_t - t0, 6)
        first = self.token_t[0] if self.token_t else first_token_t
        last = finish_t if finish_t is not None else (
            self.token_t[-1] if self.token_t else None
        )
        if first is not None and self.submit_t:
            rec["ttft_ms"] = round(1e3 * (first - self.submit_t), 3)
        if last is not None and self.submit_t:
            rec["e2e_ms"] = round(1e3 * (last - self.submit_t), 3)
        if len(self.token_t) > 1:
            ts = self.token_t
            rec["itl_ms"] = [
                round(1e3 * (b - a), 3) for a, b in zip(ts, ts[1:])
            ]
        return rec


# -- drivers ----------------------------------------------------------------


def run(spec: WorkloadSpec, target, *, instrument: bool = True,
        time_scale: float = 1.0, timeout_s: float = 120.0,
        max_concurrency: int = 32) -> LoadgenResult:
    """Replay ``spec`` against ``target`` and return the offered-load
    record. ``target`` is a bare engine (has ``step``), a router
    (``submit`` but no ``step``), or a replica/router-server base URL
    string. ``time_scale`` stretches/compresses the schedule's arrival
    offsets (0 = as fast as possible); ``instrument=False`` is the
    zero-overhead witness baseline (outcomes only, no token callbacks)."""
    schedule = build_schedule(spec)
    digest = schedule_digest(schedule)
    t0 = time.monotonic()
    if isinstance(target, str):
        records = _run_url(spec, schedule, target, instrument, time_scale,
                           timeout_s, max_concurrency)
        kind = "url"
    elif hasattr(target, "step"):
        records = _run_engine(spec, schedule, target, instrument,
                              time_scale, timeout_s)
        kind = "engine"
    elif hasattr(target, "submit"):
        records = _run_router(spec, schedule, target, instrument,
                              time_scale, timeout_s, max_concurrency)
        kind = "router"
    else:
        raise TypeError(f"unsupported loadgen target {target!r}")
    wall = time.monotonic() - t0
    records.sort(key=lambda r: r["index"])
    return LoadgenResult(
        spec=spec.to_json(), records=records, wall_s=round(wall, 6),
        digest=digest, target=kind,
    )


def _finalize_engine(cap: _Capture, t0: float, instrument: bool) -> dict:
    req = cap.handle
    return cap.record(
        t0, outcome=req.outcome or "in_flight",
        finish_reason=req.finish_reason, shed_reason=req.shed_reason,
        tokens_out=len(req.tokens), replica=req.replica,
        first_token_t=req.first_token_t, finish_t=req.finish_t,
        instrument=instrument,
    )


def _run_engine(spec, schedule, engine, instrument, time_scale, timeout_s):
    """Single-threaded bare-engine driver: the caller thread interleaves
    due submits with ``engine.step()`` — the tier-1 drill path."""
    t0 = time.monotonic()

    def submit(sched: ScheduledRequest) -> _Capture:
        cap = _Capture(sched)
        cap.submit_t = time.monotonic()
        cap.handle = engine.submit(
            sched.prompt, max_new_tokens=sched.max_new_tokens,
            seed=sched.seed, tenant=sched.tenant, priority=sched.priority,
            request_id=sched.request_id,
            on_token=cap.on_token if instrument else None,
        )
        return cap

    records: list = []
    live: list = []
    if spec.mode == "open":
        pending = list(schedule)  # already at_s-sorted
        i = 0
        while i < len(pending) or live:
            now = time.monotonic() - t0
            while i < len(pending) and pending[i].at_s * time_scale <= now:
                live.append(submit(pending[i]))
                i += 1
            progressed = engine.step()
            done = [c for c in live if c.handle.done]
            for c in done:
                live.remove(c)
                records.append(_finalize_engine(c, t0, instrument))
            if not progressed and not done:
                time.sleep(0.0005)  # idle: next arrival is in the future
            if now > timeout_s:
                break
    else:
        # closed loop without threads: per-user state machines advanced
        # between engine steps (one thread drives the engine)
        queues: dict = {}
        for s in schedule:
            queues.setdefault(s.user, []).append(s)
        current: dict = {}
        ready_at = {u: 0.0 for u in queues}
        while queues or current or live:
            now = time.monotonic() - t0
            for u in list(queues):
                if u in current or now < ready_at[u]:
                    continue
                sched = queues[u].pop(0)
                if not queues[u]:
                    del queues[u]
                cap = submit(sched)
                current[u] = cap
                live.append(cap)
            progressed = engine.step()
            reaped = False
            for u, cap in list(current.items()):
                if cap.handle.done:
                    reaped = True
                    del current[u]
                    live.remove(cap)
                    records.append(_finalize_engine(cap, t0, instrument))
                    nxt = queues.get(u)
                    think = nxt[0].think_s if nxt else 0.0
                    ready_at[u] = (time.monotonic() - t0) + think * time_scale
            if not progressed and not reaped:
                time.sleep(0.0005)  # idle: every user is thinking
            if now > timeout_s:
                break
    for cap in live:
        cap.handle.cancel()
    while any(not c.handle.done for c in live):
        if not engine.step():
            break
    records.extend(_finalize_engine(c, t0, instrument) for c in live)
    return records


def _run_router(spec, schedule, router, instrument, time_scale, timeout_s,
                max_concurrency):
    """Router driver: ``Router.submit`` is synchronous, so open-loop
    concurrency is a bounded thread pool and closed-loop concurrency is
    one thread per user (the failover-drill idiom)."""
    t0 = time.monotonic()
    records: list = []
    lock = threading.Lock()

    def issue(sched: ScheduledRequest):
        cap = _Capture(sched)
        cap.submit_t = time.monotonic()
        rr = router.submit(
            sched.prompt, max_new_tokens=sched.max_new_tokens,
            seed=sched.seed, session=sched.session, tenant=sched.tenant,
            priority=sched.priority, request_id=sched.request_id,
            timeout_s=timeout_s,
            on_token=cap.on_token if instrument else None,
        )
        rec = cap.record(
            t0, outcome=rr.outcome or "in_flight",
            finish_reason=rr.finish_reason, shed_reason=rr.shed_reason,
            tokens_out=len(rr.tokens), replica=rr.replica,
            first_token_t=rr.first_token_t, finish_t=rr.finish_t,
            instrument=instrument,
        )
        with lock:
            records.append(rec)

    threads: list = []
    if spec.mode == "open":
        gate = threading.Semaphore(max_concurrency)

        def timed(sched):
            with gate:
                issue(sched)

        for sched in schedule:
            wait = sched.at_s * time_scale - (time.monotonic() - t0)
            if wait > 0:
                time.sleep(wait)
            th = threading.Thread(target=timed, args=(sched,), daemon=True)
            th.start()
            threads.append(th)
    else:
        queues: dict = {}
        for s in schedule:
            queues.setdefault(s.user, []).append(s)

        def user_loop(items):
            for j, sched in enumerate(items):
                if j and sched.think_s:
                    time.sleep(sched.think_s * time_scale)
                issue(sched)

        for items in queues.values():
            th = threading.Thread(target=user_loop, args=(items,), daemon=True)
            th.start()
            threads.append(th)
    deadline = t0 + timeout_s
    for th in threads:
        th.join(timeout=max(0.1, deadline - time.monotonic()))
    return records


def _post_stream(base_url: str, body: dict, cap: _Capture, instrument,
                 timeout_s):
    """POST /v1/submit with ``stream: true`` and walk the JSONL event
    stream, stamping each token event client-side (the ReplicaServer /
    RouterServer wire protocol)."""
    u = urllib.parse.urlparse(base_url)
    conn = http.client.HTTPConnection(
        u.hostname, u.port or 80, timeout=timeout_s
    )
    try:
        payload = json.dumps(body).encode()
        conn.request("POST", "/v1/submit", body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        done_doc = {}
        tokens = 0
        buf = b""
        while True:
            chunk = resp.read(1)
            if not chunk:
                break
            if chunk != b"\n":
                buf += chunk
                continue
            if not buf.strip():
                continue
            ev = json.loads(buf.decode())
            buf = b""
            if ev.get("event") == "token":
                tokens += 1
                if instrument:
                    cap.on_token(ev.get("token"))
            elif ev.get("event") == "done":
                done_doc = ev
                break
        return done_doc, tokens
    finally:
        conn.close()


def _run_url(spec, schedule, base_url, instrument, time_scale, timeout_s,
             max_concurrency):
    t0 = time.monotonic()
    records: list = []
    lock = threading.Lock()

    def issue(sched: ScheduledRequest):
        cap = _Capture(sched)
        body = {
            "prompt": [int(x) for x in sched.prompt],
            "max_new_tokens": sched.max_new_tokens, "seed": sched.seed,
            "tenant": sched.tenant, "priority": sched.priority,
            "request_id": sched.request_id, "stream": True,
            "timeout_s": timeout_s,
        }
        if sched.session:
            body["session"] = sched.session
        cap.submit_t = time.monotonic()
        try:
            done, tokens = _post_stream(
                base_url, body, cap, instrument, timeout_s
            )
        except (OSError, ValueError):
            done, tokens = {"outcome": "cancelled",
                            "finish_reason": "transport_error"}, 0
        rec = cap.record(
            t0, outcome=done.get("outcome") or "in_flight",
            finish_reason=done.get("finish_reason"),
            shed_reason=done.get("shed_reason"),
            tokens_out=len(done.get("tokens") or []) or tokens,
            replica=done.get("replica"), instrument=instrument,
        )
        with lock:
            records.append(rec)

    threads: list = []
    if spec.mode == "open":
        gate = threading.Semaphore(max_concurrency)

        def timed(sched):
            with gate:
                issue(sched)

        for sched in schedule:
            wait = sched.at_s * time_scale - (time.monotonic() - t0)
            if wait > 0:
                time.sleep(wait)
            th = threading.Thread(target=timed, args=(sched,), daemon=True)
            th.start()
            threads.append(th)
    else:
        queues: dict = {}
        for s in schedule:
            queues.setdefault(s.user, []).append(s)

        def user_loop(items):
            for j, sched in enumerate(items):
                if j and sched.think_s:
                    time.sleep(sched.think_s * time_scale)
                issue(sched)

        for items in queues.values():
            th = threading.Thread(target=user_loop, args=(items,), daemon=True)
            th.start()
            threads.append(th)
    deadline = t0 + timeout_s
    for th in threads:
        th.join(timeout=max(0.1, deadline - time.monotonic()))
    return records
