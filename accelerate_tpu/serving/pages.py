"""Paged KV arena: fixed-size pages, refcounted free list, copy-on-write
prefix cache, and the n-gram drafter for speculative decoding.

The flat slot arena (``arena.py``) reserves ``max_cache_len`` of KV per
slot no matter how long the request actually is, and every request pays a
full prefill even when thousands share a templated system prompt. This
module replaces the storage layer with **pages**:

- K/V leaves become ``[num_pages, KVH, page_size, D]`` physical pages (a
  leading layer axis under ``scan_layers``); a per-slot **page table**
  ``[num_slots, pages_per_slot] int32`` maps each slot's position range
  ``[c*page_size, (c+1)*page_size)`` to a physical page. Page 0 is the
  reserved **parking page**: unallocated table entries point at it, and
  inactive slots' fused-step writes land there.
- the **free list + refcounts** live host-side (:class:`PageAllocator`);
  admission/growth/eviction are pure data changes (table-entry scatters),
  so the zero-recompile discipline of the flat arena carries over.
- the **prefix cache** (:class:`PrefixCache`) keys page-aligned prompt
  prefixes by token hash. A request whose prompt prefix is cached maps the
  shared pages into its table (refcount++) and prefills only the tail —
  near-zero TTFT for templated traffic. Shared pages are **copy-on-write**:
  the engine forks (copies) a page before the first divergent write, so a
  mutation by one slot can never perturb another slot's tokens.
- the **n-gram drafter** (:class:`NGramDrafter`) is the host-side,
  model-free proposer for speculative decoding: it looks the request's most
  recent n-gram up in its own prompt+generation history and proposes the
  continuation — free draft tokens for templated/repetitive traffic that
  the batched verify step then accepts or rolls back token-exactly.

Everything above the device helpers is plain-python/numpy bookkeeping and
imports **without jax or flax** (locked by tests/test_imports.py): a
router/scheduler tier can reason about page budgets on machines with no
accelerator stack. The device helpers (arena init, dense gather views,
page forks) import jax lazily at call time.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


# -- quantized-arena host helpers (jax-free, like everything above the
# device section: a router/admission tier sizes KV budgets on machines
# with no accelerator stack — locked by tests/test_imports.py) ------------

KV_CACHE_DTYPES = ("bf16", "int8", "int4")


def kv_cache_bits(kv_dtype) -> int:
    """Storage bits per K/V value for a ``kv_cache_dtype`` knob value
    (None/"bf16" -> 16). The host twin of
    ``utils.quantization.kv_cache_bits`` (which lives jax-side)."""
    if kv_dtype in (None, "bf16"):
        return 16
    if kv_dtype == "int8":
        return 8
    if kv_dtype == "int4":
        return 4
    raise ValueError(
        f"kv_cache_dtype must be one of {KV_CACHE_DTYPES}, got {kv_dtype!r}"
    )


def kv_payload_width(head_dim: int, kv_dtype) -> int:
    """Trailing payload dim of a K/V cache leaf: head_dim, or head_dim/2
    when int4 packs two values per byte."""
    if kv_cache_bits(kv_dtype) == 4:
        if head_dim % 2:
            raise ValueError(f"int4 KV needs an even head_dim, got {head_dim}")
        return head_dim // 2
    return head_dim


def kv_token_bytes(num_kv_heads: int, head_dim: int, kv_dtype,
                   cache_itemsize: int = 2, num_layers: int = 1) -> int:
    """HBM bytes one cached token costs across K and V (payload + the
    fp32 scale the quantized arena carries per (token, kv head)) — the
    capacity-planning number behind ``arena_hbm_bytes_per_slot`` and the
    ≥2x-slots math. ``cache_itemsize`` is the unquantized cache dtype's
    byte width (bf16 -> 2)."""
    bits = kv_cache_bits(kv_dtype)
    if bits == 16:
        per_value = num_kv_heads * head_dim * cache_itemsize
        return 2 * num_layers * per_value
    payload = num_kv_heads * kv_payload_width(head_dim, kv_dtype)
    scale = num_kv_heads * 4  # one fp32 per (token, kv head)
    return 2 * num_layers * (payload + scale)


def _digest(tokens: np.ndarray) -> bytes:
    """Stable content key for a token prefix (dtype-normalized so the same
    ids hash equally regardless of the caller's integer width)."""
    return hashlib.blake2b(
        np.ascontiguousarray(tokens, np.int32).tobytes(), digest_size=16
    ).digest()


class PageAllocator:
    """Refcounted free list over ``num_pages`` physical pages.

    Page ids ``< reserved`` are never handed out (page 0 is the parking
    page). A page is *free* iff its refcount is 0; ``alloc`` pops from the
    free list and sets refcount 1, ``retain`` adds a reference (prefix-cache
    sharing), ``release`` drops one and returns the page to the free list at
    zero. The free list is LIFO so recently-hot pages are reused first.
    """

    def __init__(self, num_pages: int, reserved: int = 1):
        if num_pages <= reserved:
            raise ValueError(
                f"num_pages ({num_pages}) must exceed reserved ({reserved})"
            )
        self.num_pages = int(num_pages)
        self.reserved = int(reserved)
        self.refs = [0] * num_pages
        self._free = list(range(num_pages - 1, reserved - 1, -1))  # pop() -> lowest id

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.num_pages - self.reserved - len(self._free)

    def alloc(self) -> Optional[int]:
        """One fresh page with refcount 1, or None when exhausted."""
        if not self._free:
            return None
        page = self._free.pop()
        self.refs[page] = 1
        return page

    def retain(self, page: int):
        if self.refs[page] < 1:
            raise ValueError(f"retain of free page {page}")
        self.refs[page] += 1

    def release(self, page: int) -> bool:
        """Drop one reference; True when the page returned to the free list."""
        if self.refs[page] < 1:
            raise ValueError(f"release of free page {page}")
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free.append(page)
            return True
        return False

    def shared(self, page: int) -> bool:
        return self.refs[page] > 1


@dataclass
class PrefixEntry:
    key: bytes
    token_len: int
    pages: tuple  # page ids covering [0, token_len)
    hits: int = 0
    last_used: int = 0
    # the entry's own token prefix + owning tenant: what the demote-on-
    # evict hook (serving/tiers.py) needs to rebuild the handoff blob
    # and attribute tier byte-seconds. None on entries inserted by
    # callers that predate tiering — those just can't demote.
    tokens: Optional[np.ndarray] = None
    tenant: str = "default"


class _GhostShadow:
    """Key-level LRU twin of a :class:`PrefixCache` at a scaled
    ``max_entries`` — entries are ``key -> [token_len, last_used]``, no
    pages, no allocator. Lookup/insert/evict follow the real cache's
    semantics exactly (longest-first probe, recency on committed hits and
    insert-touch, evict min ``last_used`` past capacity), so its hit count
    equals a brute-force ``PrefixCache(max_entries=N*base)`` replaying the
    same trace — the oracle tests/test_loadgen.py asserts against."""

    __slots__ = ("max_entries", "entries", "_clock", "hits")

    def __init__(self, max_entries: int):
        self.max_entries = int(max_entries)
        self.entries: dict = {}  # key bytes -> [token_len, last_used]
        self._clock = 0
        self.hits = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def lookup(self, n: int, dig) -> int:
        """Probe like ``PrefixCache.peek`` (longest cached length
        ``<= n`` whose prefix digest matches), self-committing the hit:
        the simulation has no engine to decline it."""
        for length in sorted({e[0] for e in self.entries.values()},
                             reverse=True):
            if length > n:
                continue
            e = self.entries.get(dig(length))
            if e is not None and e[0] == length:
                self.hits += 1
                e[1] = self._tick()
                return length
        return 0

    def insert(self, keyed_lengths):
        for length, key in keyed_lengths:
            e = self.entries.get(key)
            if e is not None:
                e[1] = self._tick()
                continue
            self.entries[key] = [length, self._tick()]
        while len(self.entries) > self.max_entries:
            victim = min(self.entries, key=lambda k: self.entries[k][1])
            del self.entries[victim]


class GhostCache:
    """Ghost-cache economics telemetry for a :class:`PrefixCache`: what
    would larger capacities recover?

    Two instruments, both keys-only (no pages, no KV bytes — the whole
    point is measuring the value of storage that does NOT exist yet):

    - **capacity shadows**: one :class:`_GhostShadow` LRU simulation per
      multiple of the real cache's ``max_entries`` (default 2x/4x/10x),
      fed the same lookup/insert stream. ``hit_ratio(m)`` is the hit
      ratio the cache WOULD have at ``m x`` capacity — compare against
      ``serving/prefix_hit_ratio``; the gap is the reuse an entry-LRU
      host/disk tier (ROADMAP item 2) would serve.
    - **reuse-after-evict distances**: every key the real cache evicts is
      remembered (bounded, eviction-ordered); when a later ``insert``
      re-registers an evicted key — a re-prefill of KV the cache already
      held, the exact waste a tier absorbs — the distance in lookups
      since eviction is recorded.

    Shadows only model capacity-driven (``max_entries``) eviction: a
    simulated larger cache is assumed to keep its entries' KV in a tier,
    so the real arena's page pressure does not apply to it.
    """

    def __init__(self, base_entries: int, multiples=(2, 4, 10),
                 max_distances: int = 4096):
        self.multiples = tuple(sorted({int(m) for m in multiples}))
        if not self.multiples or self.multiples[0] < 1:
            raise ValueError(f"bad ghost multiples {multiples!r}")
        self.shadows = {
            m: _GhostShadow(m * int(base_entries)) for m in self.multiples
        }
        self.lookups = 0
        self.reuses = 0
        self._evicted: dict = {}  # key -> lookup count at eviction
        self._evicted_cap = max(self.multiples) * int(base_entries)
        self._distances: list = []
        self._max_distances = int(max_distances)

    def observe_lookup(self, prompt: np.ndarray, limit: Optional[int] = None):
        self.lookups += 1
        n = int(prompt.size if limit is None else min(prompt.size, limit))
        memo: dict = {}

        def dig(length):
            d = memo.get(length)
            if d is None:
                d = memo[length] = _digest(prompt[:length])
            return d

        for shadow in self.shadows.values():
            shadow.lookup(n, dig)

    def observe_insert(self, keyed_lengths):
        """``keyed_lengths``: the ``(length, key)`` pairs the real
        insert computed — shared so the prompt hashes exactly once."""
        for _, key in keyed_lengths:
            at = self._evicted.pop(key, None)
            if at is not None:
                self.reuses += 1
                self._distances.append(self.lookups - at)
                if len(self._distances) > self._max_distances:
                    del self._distances[: self._max_distances // 2]
        for shadow in self.shadows.values():
            shadow.insert(keyed_lengths)

    def observe_evict(self, key: bytes):
        self._evicted[key] = self.lookups
        while len(self._evicted) > self._evicted_cap:
            del self._evicted[next(iter(self._evicted))]

    def hit_ratio(self, multiple: int) -> float:
        shadow = self.shadows[int(multiple)]
        return shadow.hits / self.lookups if self.lookups else 0.0

    def reuse_distance_quantile(self, q: float) -> float:
        if not self._distances:
            return 0.0
        xs = sorted(self._distances)
        idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return float(xs[idx])

    def gauges(self) -> dict:
        """``serving/ghost_*`` gauge fragment merged into
        ``ServingEngine.metrics()`` (and so into rollup -> Prometheus
        exposition -> fleet merge; the 2x/4x/10x ratios average across
        replicas, reuse distances take the fleet-worst)."""
        out = {}
        for m in self.multiples:
            out[f"serving/ghost_hit_ratio_{m}x"] = self.hit_ratio(m)
        out["serving/ghost_reuses"] = self.reuses
        if self._distances:
            out["serving/ghost_reuse_distance_p50"] = (
                self.reuse_distance_quantile(0.5)
            )
            out["serving/ghost_reuse_distance_p99"] = (
                self.reuse_distance_quantile(0.99)
            )
        return out


class PrefixCache:
    """Prompt-prefix -> shared-pages map, keyed by token-content hash.

    Insertion registers every page-aligned prefix of a finished prompt
    (plus the full, possibly partial-page prompt itself) as an entry; each
    entry holds one allocator reference per covered page. Lookup walks the
    cached lengths longest-first and returns the deepest entry whose token
    hash matches the new prompt — O(distinct lengths) hash probes, no
    token-by-token trie. Eviction is LRU at entry granularity; a page's
    storage is reclaimed only when every referencing entry AND every
    mapped slot has released it (the allocator's refcount).
    """

    def __init__(self, allocator: PageAllocator, page_size: int,
                 max_entries: int = 512, ghost_multiples=(2, 4, 10),
                 ghost_base_entries: Optional[int] = None,
                 on_evict=None):
        self.allocator = allocator
        self.page_size = int(page_size)
        self.max_entries = int(max_entries)
        self.entries: dict = {}  # key bytes -> PrefixEntry
        self._clock = 0
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        # demote-on-evict hook: called with the victim PrefixEntry
        # BEFORE its page refs are released (the pages are still intact
        # on device, so the hook can gather them into a lower tier)
        self.on_evict = on_evict
        # ghost-cache economics telemetry (keys only — a few dict ops per
        # lookup/insert; pass ghost_multiples=None/() to disable).
        # ghost_base_entries overrides the shadows' 1x base: with a
        # host/disk tier attached, the base is the TOTAL (HBM+host+disk)
        # entry capacity so the 2x/4x/10x ratios keep answering "would a
        # bigger cache help?" about capacity beyond what now exists,
        # instead of re-measuring the tier just built.
        self.ghost = (
            GhostCache(
                int(ghost_base_entries) if ghost_base_entries
                else self.max_entries,
                ghost_multiples,
            )
            if ghost_multiples else None
        )

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _candidate_lengths(self) -> list:
        return sorted({e.token_len for e in self.entries.values()}, reverse=True)

    def lookup(self, prompt: np.ndarray, limit: Optional[int] = None):
        """Longest cached prefix of ``prompt`` with ``token_len <= limit``.
        Returns ``(hit_len, entry)`` or ``(0, None)``. The caller maps
        ``entry.pages[: ceil(hit_len / page_size)]`` into its slot table
        (retaining each) and prefills only ``prompt[hit_len:]`` — then
        reports what it actually used via :meth:`record_hit` (the engine
        may shrink or discard a hit whose tail plan would not fit the slot
        or would cost more prefill dispatches than a cold admission, and
        the hit-ratio gauges must reflect the final decision)."""
        self.lookups += 1
        if self.ghost is not None:
            self.ghost.observe_lookup(prompt, limit)
        return self.peek(prompt, limit)

    def peek(self, prompt: np.ndarray, limit: Optional[int] = None):
        """:meth:`lookup` without side effects: the hit/lookup gauges and
        LRU recency stay untouched. The KV-handoff export path (a replica
        shipping cached pages to a peer) and router introspection probe
        with this — a probe is not serving traffic and must not skew the
        hit-ratio gauges or LRU-protect an entry it never admitted."""
        n = int(prompt.size if limit is None else min(prompt.size, limit))
        for length in self._candidate_lengths():
            if length > n:
                continue
            entry = self.entries.get(_digest(prompt[:length]))
            if entry is not None and entry.token_len == length:
                return length, entry
        return 0, None

    def record_hit(self, tokens: int, entry: Optional[PrefixEntry] = None):
        """Count a lookup hit that the caller actually committed to, with
        the (possibly shrunk) number of prefix tokens served. LRU recency
        moves here too: an entry whose hits are always declined must not
        stay LRU-protected, pinning its pages over genuinely useful ones."""
        if tokens > 0:
            self.hits += 1
            self.hit_tokens += int(tokens)
            if entry is not None:
                entry.hits += 1
                entry.last_used = self._tick()

    def insert(self, prompt: np.ndarray, pages, tenant: str = "default") -> int:
        """Register ``prompt`` (whose KV now lives in ``pages``, position
        order) at every page-aligned prefix length plus its full length.
        Each new entry retains its covered pages. Returns the number of
        entries created."""
        ps = self.page_size
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(prompt.size)
        lengths = list(range(ps, n + 1, ps))
        if n % ps:
            lengths.append(n)  # partial-page tail: the COW-fork case
        keyed = [(length, _digest(prompt[:length])) for length in lengths]
        created = 0
        for length, key in keyed:
            hit = self.entries.get(key)
            if hit is not None:
                hit.last_used = self._tick()
                continue
            n_pages = -(-length // ps)
            entry = PrefixEntry(
                key=key, token_len=length, pages=tuple(int(p) for p in pages[:n_pages]),
                last_used=self._tick(),
                tokens=prompt[:length].copy(), tenant=str(tenant or "default"),
            )
            for p in entry.pages:
                self.allocator.retain(p)
            self.entries[key] = entry
            created += 1
        if self.ghost is not None:
            self.ghost.observe_insert(keyed)
        while len(self.entries) > self.max_entries and self.evict_lru():
            pass
        return created

    def evict_lru(self) -> bool:
        """Drop the least-recently-used entry (releasing its page refs);
        False when the cache is empty. Called by the engine when the
        allocator cannot satisfy an admission or a decode-time page grow.
        With a demote hook attached, the victim's KV is offered to the
        lower tiers first — eviction demotes instead of dropping."""
        if not self.entries:
            return False
        key = min(self.entries, key=lambda k: self.entries[k].last_used)
        entry = self.entries.pop(key)
        if self.on_evict is not None:
            # pages are still retained here: the hook may gather them
            try:
                self.on_evict(entry)
            except Exception:
                # demotion is an optimization; a failing tier must never
                # turn an eviction into an engine error
                pass
        for p in entry.pages:
            self.allocator.release(p)
        if self.ghost is not None:
            self.ghost.observe_evict(key)
        return True

    def clear(self):
        while self.evict_lru():
            pass

    @property
    def hit_ratio(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0


class NGramDrafter:
    """Prompt-lookup speculative drafter (model-free, host-side).

    ``propose(context, k)`` matches the last ``order`` tokens of the
    request's prompt+generation history against earlier occurrences
    (longest order first, most recent match first) and proposes the ``k``
    tokens that followed; short/no matches pad by repeating the last token
    (a padded draft that happens to match is still token-exact — accepted
    tokens are always the *target model's* samples, drafts only decide how
    many verify in one step). Accept-rate expectations: high for
    templated/repetitive continuations (code, JSON, retrieval-grounded
    text), near zero for high-entropy sampling — the verify step then
    degrades to one-token-per-call, never to wrong tokens.
    """

    def __init__(self, order: int = 3, min_order: int = 1,
                 lookback: int = 1024):
        if order < 1 or min_order < 1 or min_order > order:
            raise ValueError(f"bad n-gram orders ({order}, {min_order})")
        if lookback < 2:
            raise ValueError(f"lookback must be >= 2, got {lookback}")
        self.order = int(order)
        self.min_order = int(min_order)
        # bound the per-proposal scan: without it the sliding-window match
        # walks the FULL prompt+generation history every verify round,
        # which is quadratic host work over a long generation
        self.lookback = int(lookback)

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        context = np.asarray(context, np.int32).reshape(-1)[-self.lookback:]
        out = np.full((k,), int(context[-1]) if context.size else 0, np.int32)
        if context.size < 2:
            return out
        for n in range(min(self.order, context.size - 1), self.min_order - 1, -1):
            pat = context[-n:]
            # most recent earlier occurrence of the n-gram
            windows = np.lib.stride_tricks.sliding_window_view(context[:-1], n)
            matches = np.nonzero((windows == pat).all(axis=1))[0]
            if matches.size == 0:
                continue
            j = int(matches[-1])
            cont = context[j + n : j + n + k]
            out[: cont.size] = cont
            return out
        return out


class PagedTables:
    """Host mirror of the device page tables: one np row per slot plus the
    allocated-entry count. Entries beyond ``alloc_count`` are parking-page
    padding (gathered but masked, never written by an active slot)."""

    def __init__(self, num_slots: int, pages_per_slot: int, parking: int = 0):
        self.num_slots = int(num_slots)
        self.pages_per_slot = int(pages_per_slot)
        self.parking = int(parking)
        self.rows = np.full((num_slots, pages_per_slot), parking, np.int32)
        self.alloc_count = [0] * num_slots

    def reset_slot(self, slot: int):
        self.rows[slot] = self.parking
        self.alloc_count[slot] = 0

    def slot_pages(self, slot: int) -> list:
        return [int(p) for p in self.rows[slot, : self.alloc_count[slot]]]


# ---------------------------------------------------------------------------
# device helpers (lazy jax: the bookkeeping above must import accelerator-free)
# ---------------------------------------------------------------------------

# paged K/V leaves are [num_pages, KVH, page_size, D] (+ layer axis). A
# quantized arena's scale leaves are [num_pages, KVH, page_size, 1] — same
# rank BY DESIGN, so every generic tree op below (gather views, scatters,
# CoW forks) moves a page's payload and its scales together with no
# special-casing, and nothing can fork or share one without the other.
_KV_NDIM = 4


def _is_kv(leaf) -> bool:
    return getattr(leaf, "ndim", 0) >= _KV_NDIM


def _page_axis(leaf) -> int:
    return leaf.ndim - _KV_NDIM


def init_paged_arena(definition, params, num_slots: int, pages_per_slot: int,
                     placer):
    """All-zeros paged cache arena shaped by ``jax.eval_shape`` over the
    paged decode apply — the paged twin of ``arena.init_arena`` (no compile,
    no device compute, correct for any cache layout the family uses)."""
    import jax
    import jax.numpy as jnp

    def shape_fn(p):
        _, mutated = definition.apply(
            {"params": placer(p)},
            jnp.zeros((num_slots, 1), jnp.int32),
            positions=jnp.zeros((num_slots, 1), jnp.int32),
            use_cache=True,
            decode=True,
            cache_positions=jnp.zeros((num_slots,), jnp.int32),
            page_table=jnp.zeros((num_slots, pages_per_slot), jnp.int32),
            mutable=["cache"],
        )
        return mutated["cache"]

    shapes = jax.eval_shape(shape_fn, params)
    return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def dense_slot_view(arena, page_row, start):
    """Batch-1 DENSE cache tree for one slot, gathered from its pages in
    position order — what chunked prefill runs against, so the per-slot
    scalar-``cache_index`` prefill path (and its chunk-exactness contract)
    is reused verbatim on the paged arena. ``cache_index`` leaves become
    ``start``, like ``arena.slot_view``. Traced-friendly."""
    import jax
    import jax.numpy as jnp

    def take(leaf):
        if not _is_kv(leaf):
            return jnp.full(leaf.shape, start, leaf.dtype)
        axis = _page_axis(leaf)
        g = jnp.take(leaf, page_row, axis=axis)       # [..., P, KVH, ps, D]
        g = jnp.moveaxis(g, axis, axis + 1)           # [..., KVH, P, ps, D]
        shape = g.shape[: axis + 1] + (g.shape[axis + 1] * g.shape[axis + 2], g.shape[-1])
        return jnp.expand_dims(g.reshape(shape), axis)  # [..., 1, KVH, P*ps, D]

    return jax.tree_util.tree_map(take, arena)


def scatter_slot_view(arena, view_tree, page_row):
    """Write a mutated dense slot view back into the pages it was gathered
    from (the inverse of :func:`dense_slot_view`). Duplicate ``page_row``
    entries (parking padding) receive byte-identical writes — a prefill
    chunk only mutates positions inside the slot's allocated span — so the
    scatter's unspecified duplicate order cannot matter. Index leaves keep
    the arena's value, mirroring ``arena.write_slot``."""
    import jax
    import jax.numpy as jnp

    def put(leaf, view):
        if not _is_kv(leaf):
            return leaf
        axis = _page_axis(leaf)
        ps = leaf.shape[-2]
        v = jnp.squeeze(view.astype(leaf.dtype), axis=axis)  # [..., KVH, P*ps, D]
        shape = v.shape[: axis + 1] + (v.shape[axis + 1] // ps, ps, v.shape[-1])
        v = jnp.moveaxis(v.reshape(shape), axis + 1, axis)   # [..., P, KVH, ps, D]
        return leaf.at[(slice(None),) * axis + (page_row,)].set(v)

    return jax.tree_util.tree_map(put, arena, view_tree)


def fork_page(arena, src, dst):
    """Copy physical page ``src`` -> ``dst`` across every K/V leaf (all
    layers) — the copy-on-write fork. Traced ``src``/``dst``: one compiled
    program forks any page."""
    import jax

    def copy(leaf):
        if not _is_kv(leaf):
            return leaf
        axis = _page_axis(leaf)
        page = jax.lax.dynamic_slice_in_dim(leaf, src, 1, axis=axis)
        return jax.lax.dynamic_update_slice_in_dim(leaf, page, dst, axis=axis)

    return jax.tree_util.tree_map(copy, arena)


def gather_pages(arena, page_ids):
    """Host copies of physical pages ``page_ids`` from every K/V leaf, in
    the order given — the KV-handoff export read. Returns a list of numpy
    arrays (one per K/V leaf, arena flatten order) whose page axis holds
    ``len(page_ids)`` entries; quantized arenas ship the int8/int4 payload
    leaves and their fp32 scale leaves alike (same rank — see the module
    note above ``_KV_NDIM``), so a handoff can never separate a payload
    from its scales. One small gather dispatch per leaf (the full arena is
    never device_get)."""
    import jax
    import jax.numpy as jnp

    ids = jnp.asarray(list(page_ids), jnp.int32)
    out = []
    for leaf in jax.tree_util.tree_leaves(arena):
        if not _is_kv(leaf):
            continue
        g = jnp.take(leaf, ids, axis=_page_axis(leaf))
        out.append(np.asarray(jax.device_get(g)))
    return out


def gather_page(arena, src):
    """Size-1 page slice of every K/V leaf at page ``src``, arena
    flatten order — the demote-on-evict read, and the exact mirror of
    :func:`install_page`'s write. Traced ``src``: one compiled program
    gathers any page, so a warmed engine demotes evicted prefixes into
    the host tier with zero recompiles (``gather_pages`` above, with
    its per-call id *list*, would compile per distinct page count)."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(arena):
        if not _is_kv(leaf):
            continue
        out.append(
            jax.lax.dynamic_slice_in_dim(leaf, src, 1, axis=_page_axis(leaf))
        )
    return out


def install_page(arena, page_tree, dst):
    """Write one physical page's worth of K/V (``page_tree``: the arena's
    pytree with every K/V leaf replaced by a size-1 page slice; non-K/V
    leaves are ignored) into page ``dst`` — the KV-handoff import write.
    Traced ``dst``: one compiled program installs any page, so a warmed
    engine imports handed-off pages with zero recompiles."""
    import jax

    def put(leaf, page):
        if not _is_kv(leaf):
            return leaf
        return jax.lax.dynamic_update_slice_in_dim(
            leaf, page.astype(leaf.dtype), dst, axis=_page_axis(leaf)
        )

    return jax.tree_util.tree_map(put, arena, page_tree)


def set_table_row(tables, slot, row):
    """Replace one slot's device page-table row (admission)."""
    return tables.at[slot].set(row)


def set_table_entry(tables, slot, idx, page):
    """Point one table entry at a physical page (growth / fork)."""
    return tables.at[slot, idx].set(page)
