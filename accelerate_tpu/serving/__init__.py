"""Continuous-batching serving: slot-arena KV cache, chunked prefill
admission, donated in-place batched decode (docs/serving.md)."""

from .arena import arena_nbytes, arena_num_slots, init_arena  # noqa: F401
from .engine import Request, ServingEngine, generate_batched  # noqa: F401
