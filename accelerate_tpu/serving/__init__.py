"""Continuous-batching serving: slot-arena KV cache (flat or paged with a
copy-on-write prefix cache), chunked prefill admission, donated in-place
batched decode, and speculative decoding (docs/serving.md).

PEP 562 lazy re-exports: ``serving.pages`` is host-side bookkeeping
(free lists, refcounts, prefix hashing, the n-gram drafter) that a
router/scheduler tier imports on machines with no accelerator stack, so
importing it must not drag the jax-heavy engine in (tests/test_imports).
"""

_EXPORTS = {
    "arena_nbytes": "arena",
    "arena_num_slots": "arena",
    "init_arena": "arena",
    "Request": "engine",
    "ServingEngine": "engine",
    "generate_batched": "engine",
    "NGramDrafter": "pages",
    "PageAllocator": "pages",
    "PrefixCache": "pages",
    "kv_cache_bits": "pages",
    "kv_token_bytes": "pages",
    "kv_quant_drift": "drift",
    # the policy tier (scheduler.py) and the fault harness (faults.py)
    # are jax-free like pages — a router tier imports them directly
    "MultiTenantScheduler": "scheduler",
    "PrefillBudgetController": "scheduler",
    "SchedulerConfig": "scheduler",
    "TenantConfig": "scheduler",
    "FaultInjector": "faults",
    "StreamDropped": "faults",
    # the multi-replica data plane: the router tier (jax-free) and the
    # per-replica HTTP wrapper (jax-free at import; wraps a live engine)
    "Router": "router",
    "RouterConfig": "router",
    "RouterServer": "router",
    "backoff_schedule": "router",
    "ReplicaServer": "replica_server",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
