"""Continuous-batching decode engine (the Orca/vLLM-style serving loop).

``generation.generate()`` is one prompt -> one prefill -> one private
decode loop; a server with N concurrent users would run N of those
serially and waste (N-1)/N of every decode step's HBM bandwidth. This
module decodes **many requests per device step** against one slot-arena
KV cache and admits/evicts requests with no shape change, so a live
engine never recompiles:

- **slot-based batched KV cache** (``arena.py``) — the model's "cache"
  collection at batch = num_slots, plus a per-slot ``lengths`` vector.
  Admission writes a slot, eviction is host bookkeeping.
- **fused batched decode step** — ONE jitted fn
  ``(params, arena, last_tokens, lengths, active, rngs)`` with the arena
  (and the per-slot state vectors) **donated**, so the multi-hundred-MB
  cache updates in place instead of doubling HBM per step.
- **chunked prefill admission** — new prompts prefill in fixed-size
  bucketed chunks, one chunk per scheduler iteration, *interleaved*
  between decode steps: a 10k-token prompt never stalls in-flight decodes
  for more than one chunk's worth of compute.
- **host-side scheduler** (``ServingEngine``) — request queue, slot
  allocator, per-request token-stream callbacks, serving metrics through
  the runtime telemetry pipeline.

Token-exactness: batched decode reuses the exact sampling helpers and the
exact masked-attention path (``ops/attention.decode_attention``) the
single-stream loop uses, with per-request RNG chains split identically —
so ``generate_batched()`` output is token-for-token equal to sequential
``generate()`` calls with the same per-request seeds (tests/test_serving).
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..generation import _sample, _sized_definition, depipeline
from ..ops.attention import (
    _PREFILL_TOKEN_BLOCK,
    decode_kernel_active,
    prefill_kernel_active,
)
from .arena import arena_nbytes, init_arena, slot_view, write_slot
from .pages import (
    NGramDrafter,
    PageAllocator,
    PagedTables,
    PrefixCache,
    dense_slot_view,
    fork_page,
    gather_page,
    init_paged_arena,
    install_page,
    kv_cache_bits,
    scatter_slot_view,
    set_table_entry,
    set_table_row,
)
from .tiers import TierConfig, TieredStore, TierEntry, entry_nbytes
from .scheduler import (
    SHED_DRAINING,
    SHED_PAGE_EXHAUSTED,
    SHED_PAGE_PRESSURE,
    MultiTenantScheduler,
    PrefillBudgetController,
    SchedulerConfig,
)


class PagePressure(RuntimeError):
    """Raised by the page allocator when nothing is left to evict —
    callers translate it into a scheduling decision (preempt a victim,
    shed a request) so a serving loop never wedges on it."""


@dataclass(eq=False)
class Request:
    """One generation request and its life-cycle state. ``tokens`` is the
    generated continuation (the prompt is not repeated); ``result()``
    returns prompt + continuation like ``generate()`` does.

    ``eq=False``: requests are identities, not values. The generated
    dataclass ``__eq__`` would compare the ``prompt`` arrays elementwise,
    making ``queue.remove(req)`` raise (ambiguous array truth) past any
    same-shape entry — which the scheduler's remove() would swallow as
    "not queued", silently breaking cancel/timeout/shed.

    Every submitted request reaches exactly one terminal ``outcome``:
    ``"finished"`` (eos or token budget), ``"shed"`` (admission control /
    load shedding / page exhaustion / drain — ``shed_reason`` says
    which), or ``"cancelled"`` (``cancel()``, ``timeout_s`` expiry, or a
    raising ``on_token`` callback). ``outcome`` is None while live;
    ``finish_reason`` carries the finer-grained cause."""

    prompt: np.ndarray
    max_new_tokens: int
    rng: jax.Array
    on_token: Optional[Callable] = None
    # engine-assigned int, or the caller's externally-supplied request_id
    # (int or str) — a router re-queuing a request across replicas keeps
    # one id so `accelerate-tpu trace` can stitch the hops back together
    id: object = -1
    tenant: str = "default"
    priority: int = 0
    deadline_s: Optional[float] = None   # scheduling hint (EDF within class)
    timeout_s: Optional[float] = None    # hard wall from submit to cancel
    replica: Optional[str] = None        # which engine served this hop

    # runtime state (engine-owned)
    tokens: list = field(default_factory=list)
    done: bool = False
    slot: Optional[int] = None
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    outcome: Optional[str] = None        # finished | shed | cancelled
    finish_reason: Optional[str] = None  # eos | budget | timeout | ...
    shed_reason: Optional[str] = None
    preemptions: int = 0
    _last_token_t: float = 0.0
    _cancel: bool = False
    _resume: Optional[dict] = None       # preempted: saved RNG row for re-admission
    # paged-arena attribution (request records carry these so
    # `accelerate-tpu trace`/`report` can attribute per-request TTFT wins)
    prefix_hit: int = 0        # prompt tokens served from the prefix cache
    pages_allocated: int = 0   # fresh pages this request consumed (forks incl.)
    spec_proposed: int = 0     # draft tokens proposed for this request
    spec_accepted: int = 0     # draft tokens accepted by verify steps
    # hierarchical KV tiering (serving/tiers.py): which tier the prefix
    # was restored from (None = HBM hit or cold), how long the restore
    # took, and how many pages it installed — the request-record hop the
    # latency waterfall's kv_restore stage attributes
    kv_restore_tier: Optional[str] = None
    kv_restore_ms: float = 0.0
    kv_restore_pages: int = 0
    # which prefill path admitted this request: "ragged" (the packed
    # flash prefill kernel / its interpreter) or "dense" (bucketed
    # chunks) — the waterfall's prefill stage annotates kernel-vs-dense
    # from this field on the request record
    prefill_kernel: Optional[str] = None

    def result(self) -> np.ndarray:
        """[prompt + generated] token ids (the ``generate()`` contract)."""
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])

    def cancel(self) -> bool:
        """Request cancellation; the engine frees the slot and pages at
        the next scheduler iteration and the request lands in the log
        with outcome ``cancelled``. False if already terminal."""
        if self.done:
            return False
        self._cancel = True
        return True


class ServingEngine:
    """Slot-based continuous-batching scheduler over one decoder model.

    ``temperature``/``top_k`` are engine-wide (they are *compiled into*
    the fused decode step; per-request sampling params would either force
    recompiles or a slower traced-sampling path). Per-request knobs are
    the prompt, ``max_new_tokens``, the RNG seed, and the streaming
    callback.

    ``page_size`` switches the KV storage to the **paged arena**
    (``pages.py``): fixed-size pages + per-slot page tables instead of a
    dense ``num_slots x max_cache_len`` block, with ``num_pages`` physical
    pages (default: capacity-equivalent to the flat arena plus the parking
    page; set it lower to overcommit — more slots per HBM byte when real
    lengths are below ``max_cache_len``). With ``prefix_cache`` on,
    admissions whose prompt prefix is cached map the shared pages
    (copy-on-write) and prefill only the tail. ``spec_draft_len=K`` adds
    speculative decoding: the host-side ``drafter`` (default
    :class:`~.pages.NGramDrafter`) proposes K tokens and ONE batched
    verify step checks all of them, emitting the longest accepted prefix
    plus one fresh token — token-exact vs. sequential decode under both
    greedy and sampled decoding (rollback is free: rejected drafts land
    beyond the frontier, where the decode mask already hides them). Spec
    reserves ``spec_draft_len`` tokens of per-slot KV headroom.

    ``kv_cache_dtype`` ("int8"/"int4"; default: the config's, else bf16)
    stores the KV arena quantized — int8/packed-int4 payloads plus a
    per-(token, kv-head) fp32 scale arena that rides every page op
    (fork/share/page-out) beside its payload. Writes quantize only the
    fresh rows (fused into the cache scatter), reads dequantize inside the
    pallas decode kernel (or the masked-dense reference), so 2-4x more
    concurrent slots fit the same KV HBM budget at an accuracy cost the
    drift harness (``serving.drift``) quantifies. Compile set and the
    zero-recompile invariant are unchanged — quantization is a cache-leaf
    dtype, not a program shape.

    The decode step and every prefill-chunk bucket compile exactly once;
    after ``mark_steady()`` the ``admission_recompiles`` property must
    stay 0 no matter what traffic arrives — admissions, prefix hits, page
    forks and speculative verify steps are all pure data changes — the
    recompile invariant the bench (`serving_admission_recompiles`) and
    tests assert.
    """

    def __init__(
        self,
        definition,
        params,
        *,
        num_slots: int = 8,
        max_cache_len: Optional[int] = None,
        prefill_chunks=(64, 256),
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        steps_per_call: int = 1,
        param_placer=None,
        donate: Optional[bool] = None,
        telemetry=None,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        prefix_cache: bool = True,
        prefix_max_entries: Optional[int] = None,
        spec_draft_len: int = 0,
        drafter=None,
        scheduler=None,
        faults=None,
        kv_cache_dtype: Optional[str] = None,
        replica: Optional[str] = None,
        kv_tiers=None,
    ):
        from ..utils.compile_cache import (
            compile_event_counters,
            ensure_persistent_compile_cache,
            install_compile_listeners,
        )

        ensure_persistent_compile_cache()
        install_compile_listeners()
        definition, params = depipeline(definition, params)
        cfg = getattr(definition, "config", None)
        if cfg is None or not hasattr(cfg, "max_cache_len"):
            raise ValueError(
                "ServingEngine needs a definition with a DecoderConfig-style "
                "config (max_cache_len/max_seq_len)"
            )
        # KV-cache storage precision: the engine knob wins, else whatever
        # the config already carries. Cloning the definition here (before
        # cache sizing) makes every program this engine compiles — prefill
        # buckets against slot views, the fused decode step, spec verify —
        # create/consume the quantized payload + scale cache leaves.
        kvq = kv_cache_dtype or getattr(cfg, "kv_cache_dtype", "bf16") or "bf16"
        kv_cache_bits(kvq)  # validate early (raises on typos)
        self.kv_cache_dtype = kvq
        if kvq != getattr(cfg, "kv_cache_dtype", "bf16"):
            definition = definition.clone(
                config=dataclasses.replace(cfg, kv_cache_dtype=kvq)
            )
            cfg = definition.config
        cap = max_cache_len or cfg.max_cache_len or cfg.max_seq_len
        if cap != cfg.max_cache_len:
            definition = _sized_definition(definition, cap)
        self.definition = definition
        self.params = params
        self.num_slots = int(num_slots)
        self.max_cache_len = int(cap)
        self.prefill_chunks = tuple(sorted(set(int(c) for c in prefill_chunks)))
        if not self.prefill_chunks or self.prefill_chunks[0] < 1:
            raise ValueError(f"bad prefill_chunks {prefill_chunks!r}")
        self.temperature = float(temperature)
        self.top_k = top_k
        self.eos_token_id = eos_token_id
        # fuse up to K decode steps into one dispatch (a lax.scan of the
        # SAME step body — bit-identical tokens): through a remote-attached
        # runtime the per-dispatch host round trip otherwise dominates
        # ms/token, the same reason build_train_step grew steps_per_call.
        # Bursts only run when they cannot delay an admission or overshoot
        # a request's budget, so scheduling behavior is unchanged.
        self.steps_per_call = max(1, int(steps_per_call))
        if param_placer is None:
            from ..utils.quantization import dequantize_params as param_placer
        self._placer = param_placer
        # buffer donation: in-place arena updates on accelerator backends;
        # CPU-sim runs keep it off (pre-0.6 jaxlibs warn-and-copy there)
        self._donate = (
            donate if donate is not None else jax.default_backend() != "cpu"
        )

        # -- paged arena / prefix cache / speculative decoding -------------
        self.page_size = int(page_size) if page_size else None
        self.spec_k = max(0, int(spec_draft_len))
        if self.spec_k and not self.page_size:
            raise ValueError(
                "speculative decoding (spec_draft_len > 0) requires the "
                "paged arena; pass page_size=..."
            )
        if self.page_size:
            if self.max_cache_len % self.page_size:
                raise ValueError(
                    f"page_size ({self.page_size}) must divide max_cache_len "
                    f"({self.max_cache_len})"
                )
            self.pages_per_slot = self.max_cache_len // self.page_size
            # default: capacity-equivalent to the flat arena (+ the parking
            # page). Overcommit by passing a smaller num_pages.
            self.num_pages = (
                int(num_pages) if num_pages
                else 1 + self.num_slots * self.pages_per_slot
            )
            if self.num_pages < 2:
                raise ValueError(f"num_pages ({self.num_pages}) must be >= 2")
            self._paged_def = definition.clone(config=dataclasses.replace(
                definition.config,
                kv_page_size=self.page_size, kv_num_pages=self.num_pages,
            ))
            self._allocator = PageAllocator(self.num_pages, reserved=1)
            self._tables_host = PagedTables(
                self.num_slots, self.pages_per_slot, parking=0
            )
            # hierarchical KV tiering (serving/tiers.py): demote-on-evict
            # host/disk/peer store under the prefix cache. A TierConfig
            # builds the store here (wired to the usage byte-seconds hook
            # and this replica's identity); a prebuilt TieredStore is
            # taken as-is; None = tiering off (evictions drop, as before)
            if isinstance(kv_tiers, TierConfig):
                self._tiers = TieredStore(
                    kv_tiers, page_size=self.page_size,
                    kv_cache_dtype=self.kv_cache_dtype,
                    replica=replica, on_bytes=self._note_tier_bytes,
                )
            else:
                self._tiers = kv_tiers
                if self._tiers is not None and self._tiers.on_bytes is None:
                    self._tiers.on_bytes = self._note_tier_bytes
            tier_entries = (
                self._tiers.config.entry_capacity() if self._tiers else 0
            )
            prefix_entries = (
                int(prefix_max_entries) if prefix_max_entries else 512
            )
            self._prefix = (
                PrefixCache(
                    self._allocator, self.page_size,
                    max_entries=prefix_entries,
                    # tier-aware ghost shadows: headroom beyond the new
                    # TOTAL (HBM+host+disk) capacity
                    ghost_base_entries=(
                        prefix_entries + tier_entries if tier_entries else None
                    ),
                    on_evict=(
                        self._demote_entry if self._tiers is not None else None
                    ),
                )
                if prefix_cache else None
            )
            self._drafter = drafter or (NGramDrafter() if self.spec_k else None)
            self._arena = init_paged_arena(
                self._paged_def, params, self.num_slots, self.pages_per_slot,
                self._placer,
            )
            # paged decode-kernel cost model (CostRegistry dynamic row):
            # the kernel's HBM read per step is the live page set, which
            # XLA's static cost_analysis (operand sizes = the whole arena)
            # cannot see — so the engine bills modeled live-page bytes and
            # flops per dispatch from its host-side lengths instead.
            from .pages import _is_kv

            kv_leaves = [
                l for l in jax.tree_util.tree_leaves(self._arena) if _is_kv(l)
            ]
            self._kv_token_bytes = sum(
                int(l.size) * l.dtype.itemsize // (self.num_pages * self.page_size)
                for l in kv_leaves
            )
            pcfg = self._paged_def.config
            # qk + pv matmuls per attended token per query row, all layers
            self._kernel_flops_per_token = (
                4 * pcfg.num_heads * pcfg.head_dim * pcfg.num_layers
            )
            self._kernel_costed = decode_kernel_active(pcfg)
            # the verify program dispatches at query width K+1, which may
            # fail the kernel's Sq gate even when the plain decode step
            # rides the kernel — a dense-fallback verify must not bill the
            # kernel's roofline row
            self._kernel_costed_verify = bool(self.spec_k) and decode_kernel_active(
                pcfg, sq=self.spec_k + 1
            )
            # packed ragged prefill (ops/attention.ragged_prefill_attention):
            # when the flash prefill kernel (or its interpreter) engages,
            # the admission planner packs every pending tail into ONE
            # ragged dispatch per scheduler iteration — token-block
            # padding only — instead of per-slot bucketed chunks. The
            # chunked path stays compiled as the fallback/oracle.
            self._ragged_prefill = prefill_kernel_active(pcfg)
            self._ragged_bt = int(
                getattr(pcfg, "prefill_kernel_block", None)
                or _PREFILL_TOKEN_BLOCK
            )
            rb = self._ragged_bt
            # fixed grid capacities compiled at warmup (the zero-recompile
            # invariant): each chunk bucket rounded up to the token block,
            # deduped. The packer picks the smallest capacity that fits
            # the round's packed tails.
            self._ragged_caps = tuple(sorted(
                {-(-int(c) // rb) * rb for c in self.prefill_chunks}
            ))
            self._page_tables = jnp.zeros(
                (self.num_slots, self.pages_per_slot), jnp.int32
            )
            table_donate = (0,) if self._donate else ()
            self._set_row = jax.jit(set_table_row, donate_argnums=table_donate)
            self._set_entry = jax.jit(set_table_entry, donate_argnums=table_donate)
            self._fork = jax.jit(
                fork_page, donate_argnums=(0,) if self._donate else ()
            )
            # KV-handoff import write (one page per dispatch, traced dst)
            self._install_page = jax.jit(
                install_page, donate_argnums=(0,) if self._donate else ()
            )
            # demote-on-evict read: install_page's mirror, traced src —
            # one compiled program gathers any page, so post-steady
            # demotions never recompile (gather_pages' per-call id list
            # would compile per distinct page count)
            self._gather_page = jax.jit(gather_page)
            self._verify_step = (
                jax.jit(self._build_verify_core(),
                        donate_argnums=(1, 2, 4, 6) if self._donate else ())
                if self.spec_k else None
            )
        else:
            self._paged_def = None
            self._prefix = None
            self._tiers = None
            self._drafter = None
            self._verify_step = None
            self._kernel_costed = False
            self._kernel_costed_verify = False
            self._ragged_prefill = False
            self._ragged_bt = _PREFILL_TOKEN_BLOCK
            self._ragged_caps = ()
            self._arena = init_arena(definition, params, self.num_slots, self._placer)
        self.page_forks = 0
        self.kv_pages_exported = 0
        self.kv_pages_imported = 0
        # hierarchical-tiering accounting: committed admission hits per
        # tier (hbm = a plain prefix hit with no restore behind it), and
        # the restore batch counters behind kv_restore_overlap_frac
        self.kv_tier_hits = {"hbm": 0, "host": 0, "disk": 0, "peer": 0}
        self.kv_restore_batches = 0
        self.kv_restore_batches_overlapped = 0
        self.kv_restores = 0
        self.kv_restores_aborted = 0
        self._restore = None  # live restore state (see _plan_restore)
        self._restored_tier = None  # transient: which tier fed the
        self._kv_paths = None       # admission being planned right now
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.prefill_chunks_skipped = 0
        # prefill padding-waste accounting (both paths dispatch FIXED row
        # counts — chunk buckets or ragged grid capacities — so waste =
        # 1 - live/dispatched is directly comparable between them):
        # the prefill_pad_waste_frac gauge and the TTFT bench read these
        self.prefill_packed_tokens = 0      # live tokens via ragged packs
        self._prefill_tokens_dispatched = 0  # live tokens, either path
        self._prefill_rows_dispatched = 0    # grid/bucket rows, either path
        self.arena_bytes = arena_nbytes(self._arena)
        self._tokens = jnp.zeros((self.num_slots,), jnp.int32)
        self._lengths = jnp.zeros((self.num_slots,), jnp.int32)
        self._rngs = jnp.zeros((self.num_slots, 2), jnp.uint32)
        self._active = np.zeros((self.num_slots,), bool)

        # -- multi-tenant scheduler / fault injection ----------------------
        # scheduler=None keeps the original FIFO deque; a SchedulerConfig
        # or MultiTenantScheduler switches submit()/step() to the policy
        # tier (weighted-fair queues, admission control, preemption, the
        # ITL-SLO prefill-budget feedback loop — scheduler.py)
        if isinstance(scheduler, SchedulerConfig):
            scheduler = MultiTenantScheduler(scheduler)
        self._sched: Optional[MultiTenantScheduler] = scheduler
        self._controller = None
        if scheduler is not None and scheduler.config.itl_slo_ms is not None:
            self._controller = PrefillBudgetController(
                scheduler.config.itl_slo_ms,
                budget=scheduler.config.prefill_budget,
                min_budget=scheduler.config.prefill_budget_min,
                max_budget=scheduler.config.prefill_budget_max,
            )
        self._faults = faults
        self._prefill_credit = 0.0
        self._draining = False
        # fleet identity: stamped onto every request record so the trace
        # CLI can stitch a re-queued request's hops across replicas
        # (ATT_REPLICA is how a launcher names its N engine processes)
        self.replica = (
            str(replica) if replica else (os.environ.get("ATT_REPLICA") or None)
        )

        self._queue: deque = deque()
        self._free = list(range(self.num_slots))[::-1]  # pop() -> slot 0 first
        self._slot_req: dict = {}
        self._admitting = None
        # request-id assignment: a plain counter under a lock (serve()
        # advertises submit() from another thread). Kept as an attribute
        # (not itertools.count) so an externally-supplied int request_id
        # can bump it PAST itself — the tracer/scheduler key per-request
        # state by id, and an auto id later colliding with a router's
        # int id would silently merge two requests' records
        import threading

        self._next_id = 0
        self._id_lock = threading.Lock()

        self._step_core = self._build_step_core()
        donate = (1, 2, 3, 5) if self._donate else ()
        self._decode_step = jax.jit(self._step_core, donate_argnums=donate)
        self._decode_bursts: dict = {}
        self._prefill_fns: dict = {}
        self._ragged_fns: dict = {}
        self._admit_state = jax.jit(_admit_state_fn)

        # metrics
        self.step_count = 0
        self.requests_completed = 0
        self.requests_shed = 0
        self.requests_cancelled = 0
        self.preemptions = 0
        self.resumptions = 0
        self.generated_tokens = 0
        self._step_samples: deque = deque(maxlen=512)  # (wall_s, tokens, steps)
        self._itl: deque = deque(maxlen=2048)  # inter-token gaps, seconds
        self._itl_emitted = 0   # lifetime gap count; the controller only
        self._itl_observed = 0  # observes when these differ (fresh data)
        self._counters = compile_event_counters
        self._steady_mark = None
        self._exe_mem: Optional[dict] = None
        self._capacity_model = None  # lazy CapacityModel (metrics())

        if telemetry is None:
            from ..telemetry import current_session

            telemetry = current_session()
        self.telemetry = telemetry
        if telemetry is not None:
            telemetry.attach_serving(self)

    # -- compiled programs -------------------------------------------------

    def _build_step_core(self):
        placer = self._placer
        temperature, top_k = self.temperature, self.top_k
        paged = self.page_size is not None
        definition = self._paged_def if paged else self.definition

        last_pos = self.max_cache_len - 1

        def step(params, arena, tokens, lengths, active, rngs, page_tables=None):
            """One batched decode step -> (arena, tokens, lengths, rngs).
            Jitted directly as the single step and scanned by the bursts."""
            # inactive slots still flow through the fused step (fixed batch)
            # but must NOT write at ``lengths``: a slot mid-admission has
            # prefill chunks landing in the arena while decode steps run
            # interleaved, and a stray write there corrupts its prefix.
            # Park them on the LAST cache position instead — any request
            # that legitimately reaches it writes its own K/V there before
            # attending, so the garbage is unreachable. (Paged: a freed
            # slot's table row is reset to the parking page, so a parked
            # write can never land in another request's page.)
            write_pos = jnp.where(active, lengths, last_pos)
            kwargs = {"page_table": page_tables} if paged else {}
            out, mutated = definition.apply(
                {"params": placer(params), "cache": arena},
                tokens[:, None],
                positions=write_pos[:, None],
                use_cache=True,
                decode=True,
                cache_positions=write_pos,
                mutable=["cache"],
                **kwargs,
            )
            logits = out["logits"][:, -1]  # [N, V]
            split = jax.vmap(jax.random.split)(rngs)  # [N, 2, 2]
            subs = split[:, 1]
            # mirror the single-stream _sample call shape ([1, V] per slot)
            # so the drawn bits — and therefore the tokens — are identical
            nxt = jax.vmap(lambda key, row: _sample(row[None], key, temperature, top_k)[0])(
                subs, logits
            )
            # frozen slots keep their token/length/rng: an inactive slot's
            # RNG chain must not advance, or a request admitted mid-flight
            # would diverge from its single-stream chain
            nxt = jnp.where(active, nxt, tokens)
            new_rngs = jnp.where(active[:, None], split[:, 0], rngs)
            new_lengths = jnp.where(active, lengths + 1, lengths)
            return mutated["cache"], nxt, new_lengths, new_rngs

        return step

    def _build_verify_core(self):
        """The speculative verify step: feed ``[last_token, d1..dK]`` per
        slot at positions ``lengths..lengths+K``, sample a candidate at
        every position with the EXACT per-step RNG subkeys the sequential
        chain would draw, and accept the longest draft prefix that matches.
        Emitted tokens are always the target model's own samples — drafts
        only decide how many verify in one dispatch — so output is
        token-exact vs. K+1 sequential steps for greedy AND sampled
        decoding. Rollback costs nothing: rejected drafts' K/V sit beyond
        the new frontier, where the decode mask already hides them and the
        next write overwrites them (the same argument that makes slot reuse
        clearing-free)."""
        placer = self._placer
        temperature, top_k = self.temperature, self.top_k
        definition = self._paged_def
        last_pos = self.max_cache_len - 1

        def verify(params, arena, tokens, drafts, lengths, active, rngs, page_tables):
            n, k = drafts.shape
            seq = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [N, K+1]
            pos = lengths[:, None] + jnp.arange(k + 1)[None, :]
            write_pos = jnp.where(active[:, None], pos, last_pos)
            out, mutated = definition.apply(
                {"params": placer(params), "cache": arena},
                seq,
                positions=write_pos,
                use_cache=True,
                decode=True,
                cache_positions=write_pos,
                page_table=page_tables,
                mutable=["cache"],
            )
            logits = out["logits"]  # [N, K+1, V]

            def chain(rng):
                # replay the sequential loop's split discipline: at each
                # step split -> (carry, sub); collect each step's sub AND
                # the carry after it, so any accepted count lands on the
                # exact chain state sequential decode would hold
                def body(r, _):
                    nxt = jax.random.split(r)
                    return nxt[0], (nxt[1], nxt[0])

                _, (subs, states) = jax.lax.scan(body, rng, None, length=k + 1)
                return subs, states  # each [K+1, 2]

            subs, states = jax.vmap(chain)(rngs)
            cand = jax.vmap(
                jax.vmap(lambda key, row: _sample(row[None], key, temperature, top_k)[0])
            )(subs, logits)  # [N, K+1]
            matched = (cand[:, :k] == drafts).astype(jnp.int32)
            m = jnp.sum(jnp.cumprod(matched, axis=1), axis=1)  # accepted drafts
            rows = jnp.arange(n)
            new_last = cand[rows, m]           # first non-matching / bonus token
            new_rngs = states[rows, m]         # chain after m+1 splits
            new_tokens = jnp.where(active, new_last, tokens)
            new_lengths = jnp.where(active, lengths + m + 1, lengths)
            new_rngs = jnp.where(active[:, None], new_rngs, rngs)
            return mutated["cache"], new_tokens, new_lengths, new_rngs, cand, m

        return verify

    def _decode_burst(self, k: int):
        """K fused decode steps in one dispatch: a lax.scan over the single
        step body, so tokens are bit-identical to K separate steps. Returns
        (arena, tokens, lengths, rngs, toks[K, N])."""
        fn = self._decode_bursts.get(k)
        if fn is not None:
            return fn
        core = self._step_core

        def burst(params, arena, tokens, lengths, active, rngs, page_tables=None):
            def body(carry, _):
                arena, tokens, lengths, rngs = carry
                arena, tokens, lengths, rngs = core(
                    params, arena, tokens, lengths, active, rngs, page_tables
                )
                return (arena, tokens, lengths, rngs), tokens

            (arena, tokens, lengths, rngs), toks = jax.lax.scan(
                body, (arena, tokens, lengths, rngs), None, length=k
            )
            return arena, tokens, lengths, rngs, toks

        fn = jax.jit(burst, donate_argnums=(1, 2, 3, 5) if self._donate else ())
        self._decode_bursts[k] = fn
        return fn

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        definition, placer = self.definition, self._placer
        temperature, top_k = self.temperature, self.top_k
        paged = self.page_size is not None

        def prefill(params, arena, chunk_ids, slot, start, last_idx, rng,
                    page_tables=None):
            # per-slot chunked prefill rides the scalar-cache_index decode
            # path: queries at global positions start..start+C-1 attend the
            # slot's full prefix — exact continuation across chunks. On the
            # paged arena the slot view is GATHERED from its pages into
            # dense position order first and scattered back after, so the
            # model-side chunk program (and its exactness contract) is the
            # same one the flat arena runs.
            if paged:
                row = jax.lax.dynamic_index_in_dim(
                    page_tables, slot, 0, keepdims=False
                )
                view = dense_slot_view(arena, row, start)
            else:
                view = slot_view(arena, slot, start)
            out, mutated = definition.apply(
                {"params": placer(params), "cache": view},
                chunk_ids,  # [1, C]
                positions=start + jnp.arange(bucket),
                use_cache=True,
                decode=True,
                mutable=["cache"],
            )
            if paged:
                arena = scatter_slot_view(arena, mutated["cache"], row)
            else:
                arena = write_slot(arena, mutated["cache"], slot)
            # first-token sample from the last VALID row (padding rows of a
            # bucketed final chunk produce garbage logits we never read)
            row_l = jax.lax.dynamic_index_in_dim(out["logits"][0], last_idx, 0, keepdims=False)
            first = _sample(row_l[None], rng, temperature, top_k)[0]
            return arena, first

        fn = jax.jit(prefill, donate_argnums=(1,) if self._donate else ())
        self._prefill_fns[bucket] = fn
        return fn

    def _ragged_prefill_fn(self, cap: int):
        fn = self._ragged_fns.get(cap)
        if fn is not None:
            return fn
        definition, placer = self._paged_def, self._placer
        temperature, top_k = self.temperature, self.top_k

        def ragged_prefill(params, arena, ids, row_slot, row_pos, slot_hist,
                           page_tables, last_rows, rngs):
            # one packed flash-prefill dispatch over the paged arena: every
            # pending tail rides the same [1, cap] token pack, the ragged
            # kernel attends each row to its slot's arena prefix plus its
            # own packed causal history, and quantize-on-write scatters
            # payload+scales through the page table in the same program.
            # Pad rows (slot/pos = -1) route to the parking page. A first
            # token is sampled for EVERY slot from ``last_rows`` — the
            # host only reads the rows of slots that actually completed a
            # tail this dispatch, so the rest are dead lanes, not hazards.
            positions = jnp.maximum(row_pos, 0)[None, :]
            out, mutated = definition.apply(
                {"params": placer(params), "cache": arena},
                ids,  # [1, cap]
                positions=positions,
                use_cache=True,
                decode=True,
                cache_positions=row_pos[None, :],
                page_table=page_tables,
                ragged_slots=row_slot,
                slot_hist=slot_hist,
                mutable=["cache"],
            )
            rows = jnp.take(out["logits"][0], last_rows, axis=0)  # [S, V]
            firsts = jax.vmap(
                lambda key, row: _sample(row[None], key, temperature, top_k)[0]
            )(rngs, rows)
            return mutated["cache"], firsts

        fn = jax.jit(ragged_prefill,
                     donate_argnums=(1,) if self._donate else ())
        self._ragged_fns[cap] = fn
        return fn

    def warmup(self):
        """Compile every program this engine can ever dispatch — each
        prefill bucket, the admission scatter, the single decode step and
        the ``steps_per_call`` burst, plus the host-side eager RNG ops —
        by running them once against the (idle) arena. After
        ``warmup(); mark_steady()``, ``admission_recompiles`` staying 0 is
        deterministic, not a function of what traffic happened to arrive.
        All-inactive decode steps park their writes (see the step body), so
        warmup leaves no observable state behind."""
        if self._slot_req or self._queued_depth() or self._admitting is not None:
            raise RuntimeError("warmup() needs an idle engine")
        rng = jax.random.PRNGKey(0)
        # the eager per-admission ops, UNPACKED like _advance_admission does:
        # iterating the split result compiles the index programs too, and
        # they must not count against the post-steady recompile invariant
        _, _ = jax.random.split(rng)
        if self.telemetry is not None:
            from ..telemetry import forensics

            # registration + the warmup fingerprints below establish the
            # steady-state signatures, so any later diagnosed recompile
            # names what the admission path changed
            forensics.register(
                "decode_step", donate=(1, 2, 3, 5) if self._donate else (),
                statics={"num_slots": self.num_slots,
                         "max_cache_len": self.max_cache_len,
                         "temperature": self.temperature, "top_k": self.top_k},
            )
        costs = getattr(self.telemetry, "costs", None)
        paged = self.page_size is not None
        pk = {"page_tables": self._page_tables} if paged else {}
        for bucket in self.prefill_chunks:
            warm_chunk = jnp.zeros((1, bucket), jnp.int32)
            self._note_forensics(f"prefill_{bucket}", {"chunk_ids": warm_chunk})
            self._arena, _ = self._prefill_fn(bucket)(
                self.params, self._arena, warm_chunk,
                0, 0, bucket - 1, rng, **pk,
            )
            if costs is not None:
                # roofline row per bucket; one re-trace, and the compiled
                # memory analysis only when the persistent cache serves it
                try:
                    costs.capture_lowered(f"prefill_{bucket}", self._prefill_fn(bucket).lower(
                        self.params, self._arena, warm_chunk, 0, 0, bucket - 1, rng, **pk,
                    ))
                except Exception:
                    pass
        if paged:
            # the page-table maintenance programs: row install (admission),
            # entry scatter (growth), page fork (copy-on-write). All traced-
            # index data ops — one compile each, any slot/page thereafter.
            # Warmup runs them as no-ops against the idle state (row 0 is
            # already parking; forking the parking page onto itself).
            self._page_tables = self._set_row(
                self._page_tables, 0, jnp.asarray(self._tables_host.rows[0])
            )
            self._page_tables = self._set_entry(self._page_tables, 0, 0, 0)
            self._arena = self._fork(self._arena, 0, 0)
            # the KV-handoff install program: write a zeros page into the
            # parking page (whose content is unreachable by construction),
            # so a post-steady import of handed-off pages never compiles
            self._arena = self._install_page(
                self._arena, self._page_slice_tree(), 0
            )
            # ... and its mirror, the demote-on-evict page gather (reads
            # the parking page; nothing observable), so a post-steady
            # eviction can demote into the host tier with zero recompiles
            jax.device_get(self._gather_page(self._arena, 0))
            if self._kernel_costed and costs is not None:
                # seed the kernel's dynamic roofline row at warmup so a
                # rollup/report taken before traffic already lists the
                # executable (wall/bytes accumulate per decode dispatch)
                costs.note_dynamic("paged_decode_kernel", 0.0, calls=0)
            if self._ragged_prefill:
                # the packed ragged-prefill programs, one per fixed grid
                # capacity. All-pad warm args are safe: both kernel kv
                # phases see zero live rows, quantize-on-write lands on
                # the parking page (unreachable by construction), and
                # the sampled firsts are discarded host-side.
                warm_hist = jnp.zeros((self.num_slots,), jnp.int32)
                warm_last = jnp.zeros((self.num_slots,), jnp.int32)
                warm_rngs = jnp.zeros((self.num_slots, 2), jnp.uint32)
                for rcap in self._ragged_caps:
                    warm_ids = jnp.zeros((1, rcap), jnp.int32)
                    warm_neg = jnp.full((rcap,), -1, jnp.int32)
                    self._note_forensics(
                        f"ragged_prefill_{rcap}", {"ids": warm_ids}
                    )
                    self._arena, _ = self._ragged_prefill_fn(rcap)(
                        self.params, self._arena, warm_ids, warm_neg,
                        warm_neg, warm_hist, self._page_tables, warm_last,
                        warm_rngs,
                    )
                    if costs is not None:
                        try:
                            costs.capture_lowered(
                                f"ragged_prefill_{rcap}",
                                self._ragged_prefill_fn(rcap).lower(
                                    self.params, self._arena, warm_ids,
                                    warm_neg, warm_neg, warm_hist,
                                    self._page_tables, warm_last,
                                    warm_rngs,
                                ))
                        except Exception:
                            pass
                if costs is not None:
                    # the kernel's dynamic roofline row, billed from
                    # host-side packed-token counts per dispatch
                    costs.note_dynamic("ragged_prefill_kernel", 0.0,
                                       calls=0)
        self._tokens, self._lengths, self._rngs = self._admit_state(
            self._tokens, self._lengths, self._rngs, 0, 0, 0, rng
        )
        self._note_forensics(
            "decode_step",
            {"tokens": self._tokens, "lengths": self._lengths,
             "active": self._active, "rngs": self._rngs},
        )
        step_extra = (self._page_tables,) if paged else ()
        self._arena, self._tokens, self._lengths, self._rngs = self._decode_step(
            self.params, self._arena, self._tokens, self._lengths, self._active,
            self._rngs, *step_extra,
        )
        if self.steps_per_call > 1:
            self._arena, self._tokens, self._lengths, self._rngs, _ = (
                self._decode_burst(self.steps_per_call)(
                    self.params, self._arena, self._tokens, self._lengths,
                    self._active, self._rngs, *step_extra,
                )
            )
        if self._verify_step is not None:
            # the speculative verify program: all-inactive, so state freezes
            warm_drafts = jnp.zeros((self.num_slots, self.spec_k), jnp.int32)
            # fingerprint the FULL steady-state arg set (what
            # _spec_verify_once notes), so a later diagnosed recompile
            # diffs against it instead of reporting every arg as new
            self._note_forensics(
                "spec_verify",
                {"tokens": self._tokens, "drafts": warm_drafts,
                 "lengths": self._lengths, "active": self._active,
                 "rngs": self._rngs},
            )
            self._arena, self._tokens, self._lengths, self._rngs, _, _ = (
                self._verify_step(
                    self.params, self._arena, self._tokens, warm_drafts,
                    self._lengths, self._active, self._rngs, self._page_tables,
                )
            )
            if costs is not None:
                # CostRegistry row for the verify executable, so the
                # speculative win is attributable in the roofline table
                try:
                    costs.capture_lowered("spec_verify", self._verify_step.lower(
                        self.params, self._arena, self._tokens, warm_drafts,
                        self._lengths, self._active, self._rngs,
                        self._page_tables,
                    ))
                except Exception:
                    pass
        jax.device_get(self._tokens)
        # snapshot the decode step's memory_analysis here on the engine
        # thread so a later flight dump never has to; the AOT re-lower hits
        # the persistent compile cache the jit call above just populated,
        # so this costs a deserialize, not a second compile
        self.executable_memory_stats()
        return self

    def audit_entrypoints(self) -> list:
        """Entry-point specs for the static program auditor
        (``accelerate_tpu.analysis.program_audit``): every program
        ``warmup()`` compiles — prefill buckets, the decode step and the
        ``steps_per_call`` burst, spec verify, the page-table maintenance
        programs — with the example args warmup itself would pass and the
        *effective* donation sets. Trace-only consumers: building the
        specs executes nothing and compiles nothing, so this is safe on
        a live engine (the jitted-fn caches it touches are the ones
        warmup populates anyway). ``donate_expected`` mirrors
        ``self._donate`` so the CPU sim's deliberate no-donation policy
        is not reported as a donation miss."""
        rng = jax.random.PRNGKey(0)
        paged = self.page_size is not None
        dtype = np.dtype(self.definition.config.dtype).name
        pk = {"page_tables": self._page_tables} if paged else {}
        donate_on = self._donate
        specs = []
        for bucket in self.prefill_chunks:
            warm_chunk = jnp.zeros((1, bucket), jnp.int32)
            specs.append(dict(
                name=f"prefill_{bucket}", fn=self._prefill_fn(bucket),
                args=(self.params, self._arena, warm_chunk, 0, 0, bucket - 1, rng),
                kwargs=dict(pk), donate=(1,) if donate_on else (),
                donate_expected=donate_on, compute_dtype=dtype,
            ))
        step_extra = (self._page_tables,) if paged else ()
        step_args = (self.params, self._arena, self._tokens, self._lengths,
                     self._active, self._rngs) + step_extra
        step_donate = (1, 2, 3, 5) if donate_on else ()
        # NB: no shape_probe on the engine's own programs, deliberately.
        # The weak-shape check compares shape-derived scalar literals
        # between two traces, and the batched per-slot RNG chains bake
        # num_slots into threefry's counter math inside jax itself — a
        # library-inherent encoding every batched-RNG program has, not a
        # user bug. The engine's zero-recompile invariant holds by fixed
        # shapes (warmup + the compile-counter tests witness it); the
        # probe-based check is for shape-polymorphic USER programs.
        specs.append(dict(
            name="decode_step", fn=self._decode_step, args=step_args,
            donate=step_donate, donate_expected=donate_on, compute_dtype=dtype,
        ))
        if self.steps_per_call > 1:
            specs.append(dict(
                name=f"decode_burst{self.steps_per_call}",
                fn=self._decode_burst(self.steps_per_call), args=step_args,
                donate=step_donate, donate_expected=donate_on,
                compute_dtype=dtype,
            ))
        if self._verify_step is not None:
            warm_drafts = jnp.zeros((self.num_slots, self.spec_k), jnp.int32)
            specs.append(dict(
                name="spec_verify", fn=self._verify_step,
                args=(self.params, self._arena, self._tokens, warm_drafts,
                      self._lengths, self._active, self._rngs,
                      self._page_tables),
                donate=(1, 2, 4, 6) if donate_on else (),
                donate_expected=donate_on, compute_dtype=dtype,
            ))
        if paged:
            table_donate = (0,) if donate_on else ()
            specs.append(dict(
                name="table_set_row", fn=self._set_row,
                args=(self._page_tables, 0,
                      jnp.asarray(self._tables_host.rows[0])),
                donate=table_donate, donate_expected=donate_on,
            ))
            specs.append(dict(
                name="table_set_entry", fn=self._set_entry,
                args=(self._page_tables, 0, 0, 0),
                donate=table_donate, donate_expected=donate_on,
            ))
            specs.append(dict(
                name="page_fork", fn=self._fork, args=(self._arena, 0, 0),
                donate=(0,) if donate_on else (), donate_expected=donate_on,
                compute_dtype=dtype,
            ))
            if self._ragged_prefill:
                warm_hist = jnp.zeros((self.num_slots,), jnp.int32)
                warm_last = jnp.zeros((self.num_slots,), jnp.int32)
                warm_rngs = jnp.zeros((self.num_slots, 2), jnp.uint32)
                for rcap in self._ragged_caps:
                    warm_ids = jnp.zeros((1, rcap), jnp.int32)
                    warm_neg = jnp.full((rcap,), -1, jnp.int32)
                    specs.append(dict(
                        name=f"ragged_prefill_{rcap}",
                        fn=self._ragged_prefill_fn(rcap),
                        args=(self.params, self._arena, warm_ids, warm_neg,
                              warm_neg, warm_hist, self._page_tables,
                              warm_last, warm_rngs),
                        donate=(1,) if donate_on else (),
                        donate_expected=donate_on, compute_dtype=dtype,
                    ))
        return specs

    # -- request API -------------------------------------------------------

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int = 32,
        seed: int = 0,
        rng: Optional[jax.Array] = None,
        on_token: Optional[Callable] = None,
        tenant: str = "default",
        priority: int = 0,
        deadline_s: Optional[float] = None,
        timeout_s: Optional[float] = None,
        request_id=None,
    ) -> Request:
        """Queue one request; returns its live :class:`Request` handle.
        ``rng``/``seed`` match ``generate(..., rng=...)``: the same seed
        yields the same tokens the single-stream loop would produce.
        ``on_token(token_id, request)`` fires as each token is emitted.

        ``request_id`` (int or str) overrides the engine-assigned id: a
        router submitting one logical request to several replicas (e.g.
        a re-queue after a replica death) passes the same id to each hop
        so the per-replica request logs stitch back into one timeline
        (``accelerate-tpu trace summary --request-id``). The caller owns
        uniqueness among its own ids; an external *int* id also bumps the
        engine's auto counter past itself, so auto-assigned ids can never
        collide with it.

        With a scheduler attached, ``tenant``/``priority``/``deadline_s``
        drive the weighted-fair, priority-classed queue, and admission
        control applies: a submit past the queue watermarks returns a
        request **already terminal with outcome ``shed``** (check
        ``req.outcome``) instead of raising — backpressure is a value,
        not an exception. ``timeout_s`` cancels the request (freeing its
        slot and pages) if it has not finished that many seconds after
        submit."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        cover = self._plan_cover(prompt.size)
        if self._sched is not None and self._sched.config.preemption:
            # a preemptible request must be re-admittable at ANY progress
            # point: the worst-case replay (prompt + all generated tokens
            # but the last) must itself chunk-plan within the slot, or a
            # resume could fail to fit mid-flight when the prefix cache
            # has nothing for it
            cover = max(
                cover, self._plan_cover(prompt.size + max_new_tokens - 1)
            )
        # speculative verify writes up to spec_k positions past the last
        # sequential write, so spec reserves that much per-slot headroom
        need = prompt.size + max_new_tokens + self.spec_k
        if need > self.max_cache_len or cover > self.max_cache_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens})"
                + (f" + spec headroom ({self.spec_k})" if self.spec_k else "")
                + f" exceeds the slot KV capacity ({self.max_cache_len}); "
                "raise max_cache_len"
            )
        with self._id_lock:
            if request_id is None:
                rid = self._next_id
                self._next_id += 1
            else:
                rid = request_id
                if isinstance(rid, int) and rid >= self._next_id:
                    # never hand this id out as an auto id later
                    self._next_id = rid + 1
        req = Request(
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            rng=rng if rng is not None else jax.random.PRNGKey(seed),
            on_token=on_token,
            id=rid,
            tenant=str(tenant or "default"),
            priority=int(priority),
            deadline_s=deadline_s,
            timeout_s=timeout_s,
            replica=self.replica,
        )
        req.submit_t = time.perf_counter()
        tr = self._tracer()
        if tr is not None:
            # before the queue append: serve() admits from another thread,
            # and admission must find the tracer record already live
            tr.on_submit(req)
        usage = self._usage()
        if usage is not None:
            usage.note_submit(req.tenant)
        if self._draining:
            self._shed(req, SHED_DRAINING)
            return req
        if self._sched is not None:
            ok, reason = self._sched.admit(req)
            if not ok:
                self._shed(req, reason)
            return req
        self._queue.append(req)
        return req

    def generate_batched(self, prompts, *, max_new_tokens: int = 32, seeds=None):
        """Submit ``prompts`` (list of 1-D id arrays), run to completion,
        return the list of [prompt + generated] arrays — the batched
        counterpart of N sequential ``generate()`` calls."""
        if seeds is None:
            seeds = range(len(prompts))
        else:
            seeds = list(seeds)
            if len(seeds) != len(prompts):
                raise ValueError(
                    f"seeds ({len(seeds)}) must match prompts ({len(prompts)})"
                )
        reqs = [
            self.submit(p, max_new_tokens=max_new_tokens, seed=s)
            for p, s in zip(prompts, seeds)
        ]
        self.run()
        # the batch API promises every output or a loud error — a request
        # shed under page pressure (with no scheduler to preempt for it)
        # must not come back as a silently truncated sequence
        bad = [r for r in reqs if r.outcome != "finished"]
        if bad:
            raise RuntimeError(
                f"generate_batched: {len(bad)}/{len(reqs)} requests did not "
                f"finish ({sorted({r.outcome for r in bad})}; first: id="
                f"{bad[0].id} shed_reason={bad[0].shed_reason}) — the arena "
                "is overcommitted for this batch; raise num_pages/num_slots "
                "or serve through submit() with a scheduler"
            )
        return [r.result() for r in reqs]

    # -- scheduler ---------------------------------------------------------

    def _queued_depth(self) -> int:
        return self._sched.total_queued if self._sched is not None else len(self._queue)

    def _pending(self) -> bool:
        return bool(
            self._queued_depth() or self._admitting is not None or self._slot_req
        )

    def step(self) -> bool:
        """One scheduler iteration: reap cancels/timeouts, apply pressure
        decisions (shed, preempt), advance prefill admission within the
        ITL-budget, then run one batched decode step over every active
        slot. Returns whether any work happened (False = fully idle)."""
        if self._faults is not None:
            self._faults.on_step(self)
        if self._draining and self._queued_depth():
            # request_drain() only sets the flag (it may fire from a
            # signal handler); the queue shed always runs here, on the
            # loop thread
            self._shed_queue_for_drain()
        progressed = self._reap()
        if self._sched is not None:
            progressed = self._shed_on_pressure() or progressed
            progressed = self._maybe_preempt() or progressed
            budget = (
                self._controller.budget if self._controller is not None
                else self._sched.config.prefill_budget
            )
            if not self._slot_req:
                # throttling prefill protects live decodes' ITL; with
                # none live there is nothing to protect — admit freely
                budget = max(budget, 1.0)
            self._prefill_credit = min(
                self._prefill_credit + budget, max(1.0, budget)
            )
            while self._prefill_credit >= 1.0:
                if not self._advance_admission():
                    break
                self._prefill_credit -= 1.0
                progressed = True
        else:
            progressed = self._advance_admission() or progressed
        progressed = self._decode_once() or progressed
        if (
            self._controller is not None
            and self._itl_emitted != self._itl_observed
        ):
            # gate on fresh gaps: idle iterations (serve() polling an
            # empty engine) must not replay the last window's p99 into
            # the controller at wall-clock rate
            self._itl_observed = self._itl_emitted
            p99, n = self._recent_itl_p99_ms()
            self._controller.observe(p99, samples=n)
        return progressed

    def _recent_itl_p99_ms(self, window: int = 128):
        """p99 over the most recent ITL gaps — the live observation the
        prefill-budget controller acts on (the lifetime histograms would
        dilute a fresh regression under hours of healthy history)."""
        if not self._itl:
            return None, 0
        recent = list(self._itl)[-window:]
        return 1e3 * float(np.percentile(np.asarray(recent), 99)), len(recent)

    def run(self):
        """Drive :meth:`step` until queue, admissions and slots are idle."""
        try:
            while self._pending():
                self.step()
        except Exception:
            self._flight_dump("serving_exception")
            raise

    def serve(self, should_stop: Optional[Callable[[], bool]] = None, idle_sleep_s: float = 0.001):
        """Long-running loop: keep scheduling as requests arrive (from
        callbacks or another thread's ``submit``) until ``should_stop()``
        returns True; idle iterations sleep ``idle_sleep_s``. A drain
        request (:meth:`request_drain` — e.g. from the SIGTERM hook)
        finishes the in-flight requests and returns even when
        ``should_stop`` never fires."""
        try:
            while should_stop is None or not should_stop():
                busy = self.step()
                if self._draining and not self._pending():
                    return
                if not busy:
                    if should_stop is None and not self._pending():
                        return
                    time.sleep(idle_sleep_s)
        except Exception:
            self._flight_dump("serving_exception")
            raise

    # -- drain / shutdown ---------------------------------------------------

    def request_drain(self):
        """Flag-only drain: stop admitting (subsequent ``submit`` sheds)
        and mark everything still queued for shedding at the top of the
        next scheduler iteration; in-flight requests finish under
        whatever loop is already driving :meth:`step`. Setting one flag
        is the entire effect, so this is safe from a signal handler or
        another thread even while the engine is mid-step — the queue
        mutation itself always happens on the loop thread."""
        self._draining = True

    def _shed_queue_for_drain(self):
        now = time.perf_counter()
        for req in (self._sched.queued() if self._sched is not None
                    else list(self._queue)):
            if self._sched is not None:
                self._sched.remove(req)
            else:
                try:
                    self._queue.remove(req)
                except ValueError:
                    continue
            req.shed_reason = SHED_DRAINING
            self._terminate(req, now, "shed", "shed")

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful shutdown: stop admitting, shed the queue, run the
        loop until every in-flight request finishes (or ``timeout_s``
        passes — the stragglers are then cancelled), and flush telemetry.
        Every request submitted before the drain ends with a definite
        outcome; none is abandoned. Returns a small summary dict."""
        self.request_drain()
        self._shed_queue_for_drain()  # owner thread: shed synchronously
        deadline = (
            time.perf_counter() + timeout_s if timeout_s is not None else None
        )
        while self._pending():
            if deadline is not None and time.perf_counter() > deadline:
                now = time.perf_counter()
                if self._admitting is not None:
                    self._abort_admission(now, "cancelled", "drain_timeout")
                for req in list(self._slot_req.values()):
                    self._terminate(req, now, "cancelled", "drain_timeout")
                break
            self.step()
        if self.telemetry is not None:
            try:
                self.telemetry.flush()
            except Exception:
                pass
        return {
            "completed": self.requests_completed,
            "shed": self.requests_shed,
            "cancelled": self.requests_cancelled,
        }

    # -- terminal transitions ----------------------------------------------

    def _release_slot(self, req: Request):
        if req.slot is None:
            return
        slot = req.slot
        self._slot_req.pop(slot, None)
        self._active[slot] = False
        if self.page_size:
            self._release_slot_pages(slot, tenant=req.tenant)
        self._free.append(slot)
        req.slot = None

    def _terminate(self, req: Request, now: float, outcome: str, reason: str):
        """The single exit for every request: exactly one terminal
        outcome, slot+pages freed, counters and tracer fed."""
        if req.done:
            return
        req.done = True
        req.outcome = outcome
        req.finish_reason = reason
        req.finish_t = now
        self._release_slot(req)
        if outcome == "finished":
            self.requests_completed += 1
        elif outcome == "shed":
            self.requests_shed += 1
        else:
            self.requests_cancelled += 1
        usage = self._usage()
        if usage is not None:
            usage.note_outcome(req.tenant, outcome)
        tr = self._tracer()
        if tr is not None:
            tr.on_finish(req, reason)

    def _shed(self, req: Request, reason: str):
        req.shed_reason = reason
        self._terminate(req, time.perf_counter(), "shed", "shed")

    def _reap(self) -> bool:
        """Process cancellations and ``timeout_s`` expiries — queued,
        admitting and live alike. A cancelled/timed-out request frees its
        slot and pages *now*, not at engine close."""
        now = time.perf_counter()
        progressed = False

        def expired(req):
            return (
                req.timeout_s is not None and now - req.submit_t > req.timeout_s
            )

        for req in list(self._slot_req.values()):
            if req._cancel or expired(req):
                self._terminate(
                    req, now, "cancelled",
                    "cancelled" if req._cancel else "timeout",
                )
                progressed = True
        if self._admitting is not None:
            req = self._admitting[0]
            if req._cancel or expired(req):
                self._abort_admission(
                    now, "cancelled", "cancelled" if req._cancel else "timeout"
                )
                progressed = True
        queued = (
            self._sched.queued() if self._sched is not None else list(self._queue)
        )
        for req in queued:
            if req._cancel or expired(req):
                if self._sched is not None:
                    self._sched.remove(req)
                else:
                    try:
                        self._queue.remove(req)
                    except ValueError:
                        continue
                self._terminate(
                    req, now, "cancelled",
                    "cancelled" if req._cancel else "timeout",
                )
                progressed = True
        return progressed

    def _abort_admission(self, now: float, outcome: str, reason: str):
        """Tear down a mid-prefill admission (cancel/timeout/page
        exhaustion): the slot returns to the free list, its partially
        prefilled pages are released, the request terminates."""
        req, slot = self._admitting[0], self._admitting[1]
        self._admitting = None
        if self._restore is not None:
            # a mid-restore abort: the target pages were allocated but
            # never published — release them here or they leak
            for p in self._restore["pages"]:
                self._allocator.release(p)
            self._restore = None
            self.kv_restores_aborted += 1
        if self.page_size:
            self._release_slot_pages(slot, tenant=req.tenant)
        self._free.append(slot)
        req.slot = None
        if outcome == "shed":
            req.shed_reason = reason if req.shed_reason is None else req.shed_reason
            self._terminate(req, now, "shed", "shed")
        else:
            self._terminate(req, now, outcome, reason)

    # -- pressure: shedding and preemption ----------------------------------

    def _page_free_frac(self) -> float:
        if not self.page_size:
            return 1.0
        usable = self.num_pages - self._allocator.reserved
        return self._allocator.free_count / max(1, usable)

    def _shed_on_pressure(self) -> bool:
        """Watermark load shedding: when the paged arena's free fraction
        drops below the configured watermark, drop the newest
        lowest-priority queued request each step (queued work that could
        not be admitted anyway) with a telemetry event."""
        if not self.page_size or self._sched.total_queued == 0:
            return False
        # prefix-cache-held pages are reclaimable, not pressure: evict LRU
        # entries first and only shed if the arena is still below the
        # watermark (i.e. the pages are pinned by live slots or a fault
        # injector, not the cache)
        while (
            self._page_free_frac() < self._sched.config.page_low_watermark
            and self._prefix is not None
            and self._prefix.evict_lru()
        ):
            pass
        if self._page_free_frac() >= self._sched.config.page_low_watermark:
            return False
        # only shed queued work that really "could not be admitted
        # anyway": a queued request that outranks a live slot is
        # preemption's job (_maybe_preempt runs right after), so bound
        # the pick to classes no live slot loses to — shedding the lone
        # high-priority interactive request while low-priority batch
        # slots pin the arena would invert priority
        live = [int(r.priority) for r in self._slot_req.values()]
        victim = self._sched.pick_shed(
            max_priority=(min(live) + 1) if live else None
        )
        if victim is None:
            return False
        self._sched.shed(victim)
        victim.shed_reason = SHED_PAGE_PRESSURE
        self._terminate(victim, time.perf_counter(), "shed", "shed")
        flight = getattr(self.telemetry, "flight", None)
        if flight is not None:
            flight.note("request_shed", request_id=victim.id,
                        reason=SHED_PAGE_PRESSURE,
                        free_frac=round(self._page_free_frac(), 4))
        return True

    def _maybe_preempt(self) -> bool:
        """Page out the lowest-priority victim slot when a strictly
        higher-priority request waits and no slot is free (at most one
        preemption per scheduler iteration)."""
        if (
            self._free or self._admitting is not None or not self._slot_req
            or self._sched.total_queued == 0
        ):
            return False
        best = self._sched.peek_priority()
        if best is None:
            return False
        victim = self._sched.pick_victim(self._slot_req.items(), best)
        if victim is None:
            return False
        self._preempt(*victim)
        return True

    def _preempt(self, slot: int, req: Request):
        """Suspend a live request: save its decode-RNG chain (a host
        transfer — no compiled program), publish its KV pages to the
        prefix cache, release the slot, and requeue it at the front of
        its class. Re-admission replays prompt+generated via the prefix
        cache (mostly hits) and restores the saved chain — token-exact
        vs. an uninterrupted run, asserted in tests."""
        # whole-array device_get then host index: jnp fancy-indexing one
        # row would compile a gather, breaking the zero-recompile invariant
        rng_row = np.asarray(jax.device_get(self._rngs))[slot].copy()
        self._slot_req.pop(slot, None)
        self._active[slot] = False
        if self.page_size:
            if self._prefix is not None and req.tokens:
                replay = np.concatenate(
                    [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
                )
                # page out THROUGH the prefix cache: the entries hold the
                # refs, so re-admission maps them back as cache hits (and
                # LRU eviction can still reclaim them under real pressure)
                self._prefix.insert(
                    replay, self._tables_host.rows[slot], tenant=req.tenant
                )
            self._release_slot_pages(slot, tenant=req.tenant)
        self._free.append(slot)
        req.slot = None
        req.preemptions += 1
        req._resume = {"rng": rng_row}
        self.preemptions += 1
        usage = self._usage()
        if usage is not None:
            usage.note_preempt(req.tenant)
        self._sched.requeue(req)
        tr = self._tracer()
        if tr is not None:
            tr.on_preempt(req)
        flight = getattr(self.telemetry, "flight", None)
        if flight is not None:
            flight.note("request_preempt", request_id=req.id, slot=slot,
                        tokens=len(req.tokens))

    def _relieve_pressure(self, req: Request, exclude_slot: int) -> bool:
        """A live slot could not grow its pages: preempt a strictly
        lower-priority victim (freeing its pages for this one) if the
        scheduler allows it. False when no victim qualifies — the caller
        sheds ``req`` instead of wedging."""
        if self._sched is None:
            return False
        victim = self._sched.pick_victim(
            ((s, r) for s, r in self._slot_req.items() if s != exclude_slot),
            int(req.priority),
        )
        if victim is None:
            return False
        self._preempt(*victim)
        return True

    # -- internals ---------------------------------------------------------

    def _tracer(self):
        """The session's request tracer, or None — the whole per-request
        tracing layer costs one attribute check when telemetry is off."""
        if self.telemetry is None:
            return None
        return getattr(self.telemetry, "requests", None)

    def _usage(self):
        """The session's per-tenant usage accountant, or None — the same
        one-attribute-check contract as the tracer (telemetry/usage.py)."""
        if self.telemetry is None:
            return None
        return getattr(self.telemetry, "usage", None)

    def _note_forensics(self, fn: str, tree):
        """Fingerprint one compiled-program dispatch for recompile
        forensics; one attribute check when telemetry is off (the engine's
        no-recompile invariant means a diagnosed cause here IS a bug)."""
        if self.telemetry is None:
            return
        from ..telemetry import forensics

        forensics.note_call(fn, tree)

    def _flight_dump(self, reason: str):
        flight = getattr(self.telemetry, "flight", None)
        if flight is not None:
            try:
                flight.dump(reason)
            except Exception:
                pass

    def flight_dump(self, reason: str) -> bool:
        """Capture a flight-recorder debug bundle now (the public face of
        the internal hook — ``POST /v1/flight`` on a ReplicaServer and
        the canary's failing-probe action both land here). Returns
        whether a flight recorder exists to dump to."""
        has_flight = getattr(self.telemetry, "flight", None) is not None
        self._flight_dump(str(reason))
        return has_flight

    def _plan_chunks(self, prompt_len: int):
        """(start, bucket) list covering [0, prompt_len) from the fixed
        bucket set — largest bucket that fits, smallest (padded) for the
        tail. A bounded bucket set means a bounded compile set: admission
        at ANY prompt length reuses these programs."""
        plan, start = [], 0
        while start < prompt_len:
            rem = prompt_len - start
            fit = [c for c in self.prefill_chunks if c <= rem]
            bucket = fit[-1] if fit else self.prefill_chunks[0]
            plan.append((start, bucket))
            start += bucket
        return plan

    def _plan_cover(self, prompt_len: int) -> int:
        plan = self._plan_chunks(prompt_len)
        start, bucket = plan[-1]
        return start + bucket

    # -- paged-arena bookkeeping -------------------------------------------

    def _alloc_page(self) -> int:
        """One fresh page, evicting LRU prefix-cache entries under
        pressure. Exhaustion with nothing left to evict raises
        :class:`PagePressure`, which the admission/decode paths translate
        into a scheduling decision (preempt a victim, shed the request)
        — never an exception out of ``step()``."""
        page = self._allocator.alloc()
        while page is None and self._prefix is not None and self._prefix.evict_lru():
            page = self._allocator.alloc()
        if page is None:
            raise PagePressure(
                f"paged KV arena exhausted ({self.num_pages} pages, "
                f"{len(self._slot_req)} live slots): raise num_pages or "
                "lower num_slots/max_new_tokens for this overcommit ratio"
            )
        return page

    def _ensure_writable(self, req, slot: int, lo_pos: int, hi_pos: int):
        """Before a dispatch that writes positions [lo_pos, hi_pos] for
        ``slot``: grow its page table to cover hi_pos, and copy-on-write
        fork any page in the write range that is shared (prefix cache or
        another slot still references it). Pure data changes: a table-entry
        scatter per new page and one fork program per copy."""
        th = self._tables_host
        ps = self.page_size
        usage = self._usage()
        p_hi = hi_pos // ps
        while th.alloc_count[slot] <= p_hi:
            idx = th.alloc_count[slot]
            page = self._alloc_page()
            th.rows[slot][idx] = page
            th.alloc_count[slot] = idx + 1
            self._page_tables = self._set_entry(self._page_tables, slot, idx, page)
            req.pages_allocated += 1
            if usage is not None:
                # growth: one more page held; a CoW fork below is held-
                # count-neutral (fresh page replaces the shared claim)
                usage.note_pages(req.tenant, 1)
        for idx in range(lo_pos // ps, p_hi + 1):
            page = int(th.rows[slot][idx])
            if not self._allocator.shared(page):
                continue
            fresh = self._alloc_page()
            self._arena = self._fork(self._arena, page, fresh)
            self._allocator.release(page)
            th.rows[slot][idx] = fresh
            self._page_tables = self._set_entry(self._page_tables, slot, idx, fresh)
            req.pages_allocated += 1
            self.page_forks += 1

    def _paged_admit_plan(self, req: Request, slot: int, seq: np.ndarray) -> list:
        """Map the longest cached prefix of ``seq`` into the slot's fresh
        page table (refcount++ per shared page) and return the chunk plan
        for the UNCACHED tail only — the prefix-cache TTFT win. ``seq``
        is the prompt on a fresh admission, or prompt+generated on a
        preemption resume (whose pages the page-out published, so the
        replay is mostly hits). At least the final token always prefills:
        its logits seed the first sampled token (discarded on resume).
        Returns [(global_start, bucket), ...]."""
        th = self._tables_host
        th.reset_slot(slot)
        cold_chunks = len(self._plan_chunks(seq.size))
        hit_len = 0
        entry = None
        if self._prefix is not None:
            hit_len, entry = self._prefix.lookup(seq, limit=seq.size - 1)
            # the tail plan must still fit the slot (its padded cover can
            # exceed the whole-prompt cover when the tail is tiny)
            while hit_len and (
                hit_len + self._plan_cover(seq.size - hit_len)
                > self.max_cache_len
            ):
                hit_len = max(0, hit_len - self.page_size)
            # a hit whose tail needs MORE prefill dispatches than the cold
            # plan (e.g. cached 64 of a 256 prompt that cold-plans as one
            # 256 chunk but tail-plans as three 64s) is a TTFT loss, not a
            # win — decline it
            if hit_len and (
                len(self._plan_chunks(seq.size - hit_len)) > cold_chunks
            ):
                hit_len = 0
            if hit_len == 0:
                entry = None
            self._prefix.record_hit(hit_len, entry)
        usage = self._usage()
        if entry is not None:
            n_map = -(-hit_len // self.page_size)
            for i in range(n_map):
                page = int(entry.pages[i])
                self._allocator.retain(page)
                th.rows[slot][i] = page
            th.alloc_count[slot] = n_map
            if usage is not None:
                usage.note_pages(req.tenant, n_map)
        if usage is not None and hit_len:
            usage.note_prefix_hit(req.tenant, hit_len)
        if hit_len:
            # tier attribution: a hit right after a restore belongs to
            # the tier that supplied the pages; every other committed
            # hit was HBM-resident all along
            self.kv_tier_hits[self._restored_tier or "hbm"] += 1
        req.prefix_hit = hit_len
        if hit_len:
            # prefill chunks the cached prefix made unnecessary (TTFT
            # attribution; the cold plan is what a miss would have run)
            self.prefill_chunks_skipped += cold_chunks - len(
                self._plan_chunks(seq.size - hit_len)
            )
        self._page_tables = self._set_row(
            self._page_tables, slot, jnp.asarray(th.rows[slot])
        )
        tail_plan = self._plan_chunks(seq.size - hit_len)
        return [(hit_len + start, bucket) for start, bucket in tail_plan]

    def _insert_prefix(self, req: Request, slot: int):
        """Admission finished: publish this prompt's pages to the prefix
        cache (every page-aligned prefix + the full prompt). The request's
        own boundary page becomes shared here — its first decode write
        into that page forks it, leaving the cached copy pristine."""
        if self._prefix is None:
            return
        n_pages = -(-req.prompt.size // self.page_size)
        if n_pages > self._tables_host.alloc_count[slot]:
            return  # cannot happen post-prefill; guard for safety
        self._prefix.insert(
            req.prompt, self._tables_host.rows[slot], tenant=req.tenant
        )

    def _release_slot_pages(self, slot: int, tenant: Optional[str] = None):
        """Eviction: drop the slot's page references (pages still retained
        by the prefix cache or another slot survive) and point its device
        table row back at the parking page, so a later all-inactive fused
        step can never write into a page that was reallocated."""
        th = self._tables_host
        pages = th.slot_pages(slot)
        for page in pages:
            self._allocator.release(page)
        if tenant is not None and pages:
            usage = self._usage()
            if usage is not None:
                usage.note_pages(tenant, -len(pages))
        th.reset_slot(slot)
        self._page_tables = self._set_row(
            self._page_tables, slot, jnp.asarray(th.rows[slot])
        )

    # -- hierarchical KV tiering (HBM -> host -> disk -> peers) -------------

    def _note_tier_bytes(self, tenant: str, tier: str, delta: int):
        """TieredStore byte-movement hook -> the usage accountant's
        per-tenant tier byte-seconds meter (same symmetric contract as
        note_pages: every + has a matching -, held bytes drain to 0)."""
        if getattr(self, "telemetry", None) is None:
            # the disk-tier scan runs during __init__, before the
            # telemetry attribute lands — nothing to meter yet
            return
        usage = self._usage()
        if usage is not None:
            usage.note_tier_bytes(tenant, tier, delta)

    def _demote_entry(self, entry):
        """PrefixCache ``on_evict`` hook: gather the victim entry's
        pages off the arena (per-page through the warmup-compiled
        gather program — zero recompiles post-steady) and offer them to
        the host tier. Skips entries a tier already covers (a longer
        demoted entry serves every shorter aligned prefix), so the
        per-length cache entries never store the same pages twice."""
        tiers = self._tiers
        if tiers is None or entry.tokens is None or tiers.covers(entry.key):
            return
        if self._kv_paths is None:
            self._kv_paths = [p for p, _ in self._kv_leaf_specs()]
        from .pages import _page_axis as _pa

        per_page = [
            jax.device_get(self._gather_page(self._arena, int(p)))
            for p in entry.pages
        ]
        arrays = [
            np.concatenate([pp[i] for pp in per_page], axis=_pa(per_page[0][i]))
            for i in range(len(per_page[0]))
        ]
        tokens = np.asarray(entry.tokens, np.int32)
        tiers.put(TierEntry(
            key=entry.key, token_len=entry.token_len, tokens=tokens,
            n_pages=len(entry.pages), arrays=arrays, paths=self._kv_paths,
            nbytes=entry_nbytes(arrays, tokens), tenant=entry.tenant,
        ))

    def _plan_restore(self, req: Request, seq: np.ndarray) -> Optional[dict]:
        """Probe the lower tiers for a prefix of ``seq`` longer than the
        HBM cache's own best and, on a hit, allocate its target pages.
        Returns the restore state ``_advance_restore`` drives, or None
        (cold admission). Page pressure aborts the restore — a restore
        is an optimization, never worth shedding or preempting live
        work for — and the admission falls back to a cold prefill."""
        tiers = self._tiers
        if tiers is None or self._prefix is None or seq.size < 2:
            return None
        limit = seq.size - 1
        hbm_len, _ = self._prefix.peek(seq, limit)
        hit = tiers.probe(seq, limit, min_len=hbm_len)
        if hit is None:
            return None
        if hit["tier"] == "peer":
            try:
                tokens, token_len, _, arrays = self._handoff_arrays(
                    hit["handoff"]
                )
            except ValueError:
                self.kv_restores_aborted += 1
                return None
        else:
            tokens, arrays = hit["tokens"], hit["arrays"]
            token_len = hit["token_len"]
        # the same commit heuristics _paged_admit_plan applies to an HBM
        # hit, applied BEFORE paying for the restore: a hit the admit
        # plan would shrink or decline must not install pages first
        cold_chunks = len(self._plan_chunks(seq.size))
        hit_len = int(token_len)
        while hit_len and (
            hit_len + self._plan_cover(seq.size - hit_len) > self.max_cache_len
        ):
            hit_len = max(0, hit_len - self.page_size)
        if hit_len and (
            len(self._plan_chunks(seq.size - hit_len)) > cold_chunks
        ):
            hit_len = 0
        if hit_len <= hbm_len:
            return None
        n_pages = -(-hit_len // self.page_size)
        pages = []
        try:
            for _ in range(n_pages):
                pages.append(self._alloc_page())
        except PagePressure:
            for p in pages:
                self._allocator.release(p)
            self.kv_restores_aborted += 1
            return None
        return {
            "tier": hit["tier"],
            "tokens": np.asarray(tokens[:hit_len], np.int32),
            "arrays": arrays, "pages": pages, "next": 0,
            "t0": time.perf_counter(),
        }

    def _advance_restore(self, req: Request, slot: int, seq: np.ndarray):
        """One restore slice: install up to ``restore_batch_pages``
        pages through the warmup-compiled install program (async
        dispatches — the following ``_decode_once`` in the same
        scheduler iteration overlaps them with live slots' decode
        steps, the PR 2 dispatch-pipeline discipline). When the last
        page lands, the prefix registers in the HBM cache and the
        admission proceeds as a plain prefix hit — restored-hit ≡
        never-evicted hit, bit-for-bit."""
        r = self._restore
        batch = max(1, int(self._tiers.config.restore_batch_pages))
        overlapped = bool(self._slot_req)
        end = min(r["next"] + batch, len(r["pages"]))
        for i in range(r["next"], end):
            self._arena = self._install_page(
                self._arena, self._page_slice_tree(r["arrays"], i),
                r["pages"][i],
            )
        r["next"] = end
        self.kv_restore_batches += 1
        if overlapped:
            self.kv_restore_batches_overlapped += 1
        if end < len(r["pages"]):
            return
        # all pages installed: publish to the prefix cache (entries take
        # the refs), stamp the request's restore hop, and plan the
        # admission — whose lookup now takes the freshly restored hit
        self._prefix.insert(r["tokens"], r["pages"], tenant=req.tenant)
        for p in r["pages"]:
            self._allocator.release(p)
        if r["tier"] == "peer":
            self.kv_pages_imported += len(r["pages"])
        self.kv_restores += 1
        req.kv_restore_tier = r["tier"]
        req.kv_restore_pages = len(r["pages"])
        req.kv_restore_ms = round((time.perf_counter() - r["t0"]) * 1e3, 3)
        self._restore = None
        self._restored_tier = r["tier"]
        try:
            self._admitting[2] = self._paged_admit_plan(req, slot, seq)
        finally:
            self._restored_tier = None

    def kv_directory(self) -> dict:
        """Digest directory of this replica's exportable (HBM-cached)
        prefixes — what ``GET /v1/kv/directory`` serves and peers'
        TieredStores poll before pulling over ``/v1/kv/export``. Digest
        is the prefix cache's content key (blake2b-16 of the int32
        token bytes), hex-encoded; a peer holding the same tokens
        computes the same digest locally, so no token lists travel
        until a pull actually happens."""
        prefixes = []
        if self._prefix is not None:
            for entry in self._prefix.entries.values():
                prefixes.append({
                    "digest": entry.key.hex(),
                    "token_len": int(entry.token_len),
                })
        return {
            "version": 1, "replica": self.replica,
            "page_size": self.page_size or 0,
            "kv_cache_dtype": self.kv_cache_dtype,
            "prefixes": prefixes,
        }

    # -- KV handoff (prefill -> decode replicas, session migration) ---------

    def _page_slice_tree(self, arrays=None, page_index: int = 0):
        """Pytree matching the arena where every K/V leaf is a size-1
        page slice — what the compiled install program consumes. With
        ``arrays`` (the per-leaf host arrays a handoff carries, arena
        flatten order), the slice is that payload's ``page_index``-th
        page; without, zeros (the warmup compile). Non-K/V leaves become
        fresh zeros so nothing aliases the donated arena."""
        from .pages import _is_kv, _page_axis

        flat, treedef = jax.tree_util.tree_flatten(self._arena)
        it = iter(arrays) if arrays is not None else None
        leaves = []
        for leaf in flat:
            if _is_kv(leaf):
                axis = _page_axis(leaf)
                if it is None:
                    shape = list(leaf.shape)
                    shape[axis] = 1
                    leaves.append(jnp.zeros(shape, leaf.dtype))
                else:
                    leaves.append(
                        jnp.asarray(np.take(next(it), [page_index], axis=axis))
                    )
            else:
                leaves.append(jnp.zeros(leaf.shape, leaf.dtype))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _kv_leaf_specs(self) -> list:
        """(path, leaf) for every K/V leaf, arena flatten order — the
        handoff wire format's leaf identity (payloads AND scale arenas:
        same rank by design, so they always travel together)."""
        from .pages import _is_kv

        flat, _ = jax.tree_util.tree_flatten_with_path(self._arena)
        return [
            (jax.tree_util.keystr(path), leaf)
            for path, leaf in flat if _is_kv(leaf)
        ]

    def export_prefix_kv(self, tokens) -> Optional[dict]:
        """Export the longest cached prefix of ``tokens`` as a KV handoff:
        the quantized payload+scales pages shipped VERBATIM (bytes off the
        arena, no dequant/requant round trip — the PR 10 wire format), so
        an importing replica admits the prefix bit-identically to a local
        warm-cache hit. Returns None when nothing is cached. A prefill
        replica calls this for a finished prompt; a router calls it to
        migrate a session's KV off a draining replica. The probe uses
        ``PrefixCache.peek`` — exports never skew the hit gauges."""
        if not self.page_size or self._prefix is None:
            raise ValueError(
                "KV handoff needs the paged arena with the prefix cache "
                "(page_size=..., prefix_cache=True)"
            )
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            return None
        hit_len, entry = self._prefix.peek(tokens)
        if not hit_len:
            return None
        import base64

        n_pages = -(-hit_len // self.page_size)
        ids = [int(p) for p in entry.pages[:n_pages]]
        # per-page through the warmup-compiled gather (same as demotion):
        # gather_pages' per-call id list would compile per distinct page
        # count, and a donor serving peer pulls exports in steady state
        from .pages import _page_axis as _pa

        per_page = [
            jax.device_get(self._gather_page(self._arena, p)) for p in ids
        ]
        gathered = [
            np.concatenate([pp[i] for pp in per_page],
                           axis=_pa(per_page[0][i]))
            for i in range(len(per_page[0]))
        ]
        leaves = []
        for (path, leaf), pages in zip(self._kv_leaf_specs(), gathered):
            leaves.append({
                "path": path,
                "dtype": pages.dtype.name,
                "shape": list(pages.shape),
                "data": base64.b64encode(
                    np.ascontiguousarray(pages).tobytes()
                ).decode("ascii"),
            })
        self.kv_pages_exported += n_pages
        return {
            "version": 1,
            "page_size": self.page_size,
            "kv_cache_dtype": self.kv_cache_dtype,
            "token_len": int(hit_len),
            "tokens": [int(t) for t in tokens[:hit_len]],
            "n_pages": n_pages,
            "replica": self.replica,
            "leaves": leaves,
        }

    def _handoff_arrays(self, handoff: dict):
        """Validate a KV handoff dict against this arena's wire
        identity (version, page size, KV dtype, leaf layout) and decode
        its payload. Returns ``(tokens, token_len, n_pages, arrays)``;
        raises ValueError on any mismatch. Shared by the import
        endpoint and the peer-tier restore path — one validator, so a
        peer pull can never install what an import would reject."""
        if handoff.get("version") != 1:
            raise ValueError(f"unknown KV handoff version {handoff.get('version')!r}")
        if int(handoff["page_size"]) != self.page_size:
            raise ValueError(
                f"KV handoff page_size {handoff['page_size']} != engine "
                f"page_size {self.page_size}"
            )
        if (handoff.get("kv_cache_dtype") or "bf16") != self.kv_cache_dtype:
            raise ValueError(
                f"KV handoff kv_cache_dtype {handoff.get('kv_cache_dtype')!r} "
                f"!= engine {self.kv_cache_dtype!r}"
            )
        tokens = np.asarray(handoff["tokens"], np.int32).reshape(-1)
        token_len = int(handoff["token_len"])
        n_pages = int(handoff["n_pages"])
        if tokens.size != token_len or n_pages != -(-token_len // self.page_size):
            raise ValueError("KV handoff token/page accounting is inconsistent")
        import base64

        from .pages import _page_axis

        specs = self._kv_leaf_specs()
        wire = handoff["leaves"]
        if len(wire) != len(specs):
            raise ValueError(
                f"KV handoff carries {len(wire)} K/V leaves, engine arena "
                f"has {len(specs)} — different model/cache layout"
            )
        arrays = []
        for (path, leaf), spec in zip(specs, wire):
            axis = _page_axis(leaf)
            expect = list(leaf.shape)
            expect[axis] = n_pages
            arr = np.frombuffer(
                base64.b64decode(spec["data"]), np.dtype(spec["dtype"])
            ).reshape(spec["shape"])
            if spec["path"] != path or list(arr.shape) != expect \
                    or arr.dtype != leaf.dtype:
                raise ValueError(
                    f"KV handoff leaf {spec['path']} "
                    f"({spec['dtype']}{spec['shape']}) does not match engine "
                    f"leaf {path} ({leaf.dtype.name}, page-gathered {expect})"
                )
            arrays.append(arr)
        return tokens, token_len, n_pages, arrays

    def import_prefix_kv(self, handoff: dict) -> int:
        """Install a peer's KV handoff into this arena's prefix cache:
        allocate pages, write each payload page through the (warmup-
        compiled) install program, register the token prefix — so the
        next admission of those tokens takes the prefix-hit path exactly
        as if this replica had prefilled them itself. Returns the token
        length now served from cache (0 when page pressure blocked the
        install — a handoff is an optimization, never worth shedding live
        work for). Raises ValueError on an incompatible wire format
        (page size, KV dtype, or leaf layout mismatch)."""
        if not self.page_size or self._prefix is None:
            raise ValueError(
                "KV handoff needs the paged arena with the prefix cache "
                "(page_size=..., prefix_cache=True)"
            )
        tokens, token_len, n_pages, arrays = self._handoff_arrays(handoff)
        have, _ = self._prefix.peek(tokens)
        if have >= token_len:
            return have  # already cached at least this deep: nothing to do
        pages = []
        try:
            for _ in range(n_pages):
                pages.append(self._alloc_page())
        except PagePressure:
            for p in pages:
                self._allocator.release(p)
            return 0
        for i, dst in enumerate(pages):
            self._arena = self._install_page(
                self._arena, self._page_slice_tree(arrays, i), dst
            )
        self._prefix.insert(tokens, pages)
        # the cache entries hold the refs now; drop the allocation refs so
        # LRU eviction can reclaim the pages under real pressure
        for p in pages:
            self._allocator.release(p)
        self.kv_pages_imported += n_pages
        return token_len

    def _pop_next(self) -> Optional[Request]:
        """Next request to admit: the scheduler's WFQ/priority pick, or
        the FIFO head. Lazily skips requests that went terminal while
        queued (cancel racing the pop)."""
        while True:
            if self._sched is not None:
                req = self._sched.next_request()
            else:
                req = self._queue.popleft() if self._queue else None
            if req is None or not req.done:
                return req

    def _replay_seq(self, req: Request) -> np.ndarray:
        """The token sequence a preemption resume must re-prefill: the
        prompt plus every generated token except the last (whose K/V the
        next decode step writes — exactly the state the slot held when it
        was paged out)."""
        if not req.tokens:
            return req.prompt
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
        )

    def _advance_admission(self) -> bool:
        tr = self._tracer()
        if self._admitting is None:
            if not self._free:
                return False
            req = self._pop_next()
            if req is None:
                return False
            slot = self._free.pop()
            if req._resume is not None:
                # preemption resume: replay prompt+generated (mostly
                # prefix-cache hits — the page-out published those pages),
                # discard the trailing sample, restore the saved RNG chain.
                # req.rng is reused as the (ignored) prefill sample key: a
                # concrete array, so no fresh eager op can recompile.
                seq = self._replay_seq(req)
                prefill_rng = req.rng
                decode_rng = jnp.asarray(req._resume["rng"])
            else:
                seq = req.prompt
                prefill_rng, decode_rng = jax.random.split(req.rng)
            if self.page_size:
                # tier probe BEFORE the admit plan: a host/disk/peer hit
                # longer than HBM's best sets up a staged restore (plan
                # None until the pages land); otherwise plan immediately
                restore = self._plan_restore(req, seq)
                if restore is not None:
                    self._restore = restore
                    plan = None
                else:
                    plan = self._paged_admit_plan(req, slot, seq)
            else:
                plan = self._plan_chunks(seq.size)
            self._admitting = [req, slot, plan, 0, prefill_rng, decode_rng, seq]
            if tr is not None:
                if req._resume is not None:
                    tr.on_resume(req, slot)
                else:
                    tr.on_admission(req, slot, time.perf_counter() - req.submit_t)
        req, slot, plan, idx, prefill_rng, decode_rng, seq = self._admitting
        if plan is None:
            # restore in flight: one page batch per scheduler iteration,
            # so the decode step right after overlaps the installs
            self._advance_restore(req, slot, seq)
            return True
        if self._ragged_prefill:
            # flash prefill kernel engaged: one packed ragged dispatch
            # replaces this iteration's bucket chunk (and may co-admit
            # further queued tails into the same grid)
            return self._ragged_advance(tr)
        start, bucket = plan[idx]
        chunk = np.zeros((1, bucket), np.int32)
        seg = seq[start:start + bucket]
        chunk[0, : seg.size] = seg
        last_idx = min(seq.size, start + bucket) - 1 - start
        chunk_dev = jnp.asarray(chunk)
        self._note_forensics(f"prefill_{bucket}", {"chunk_ids": chunk_dev})
        if self._faults is not None:
            self._faults.before_prefill(self)
        t0 = time.perf_counter()
        if self.page_size:
            try:
                self._ensure_writable(req, slot, start, start + bucket - 1)
            except PagePressure:
                # same ladder as live-slot growth (_grow_or_resolve): LRU
                # eviction already failed inside _ensure_writable, so try
                # paging out a strictly lower-priority victim before
                # giving up — shedding the admission first would drop the
                # highest-priority work under pressure. Only when no
                # victim qualifies is the admission shed (never a raise
                # out of step())
                resolved = self._relieve_pressure(req, slot)
                if resolved:
                    try:
                        self._ensure_writable(req, slot, start, start + bucket - 1)
                    except PagePressure:
                        resolved = False
                if not resolved:
                    self._abort_admission(
                        time.perf_counter(), "shed", SHED_PAGE_EXHAUSTED
                    )
                    flight = getattr(self.telemetry, "flight", None)
                    if flight is not None:
                        flight.note("request_shed", request_id=req.id,
                                    reason=SHED_PAGE_EXHAUSTED)
                    return True
            self._arena, first = self._prefill_fn(bucket)(
                self.params, self._arena, chunk_dev, slot, start, last_idx,
                prefill_rng, page_tables=self._page_tables,
            )
        else:
            self._arena, first = self._prefill_fn(bucket)(
                self.params, self._arena, chunk_dev, slot, start, last_idx,
                prefill_rng,
            )
        wall = time.perf_counter() - t0
        if tr is not None:
            tr.on_prefill_chunk(req, slot, start, bucket, t0, wall)
        if self.telemetry is not None and getattr(self.telemetry, "costs", None) is not None:
            self.telemetry.costs.note_wall(f"prefill_{bucket}", wall)
        usage = self._usage()
        if usage is not None:
            # actual tokens this chunk prefilled (padding excluded) plus
            # the dispatch wall, billed to the admitting tenant
            usage.note_prefill(req.tenant, int(seg.size))
            usage.note_compute(req.tenant, wall * 1e3)
        # pad-waste accounting, comparable with the ragged path: the
        # bucket is the dispatched row count, the segment is what's live
        self._prefill_rows_dispatched += bucket
        self._prefill_tokens_dispatched += int(seg.size)
        idx += 1
        if idx < len(plan):
            self._admitting[3] = idx
            return True
        # final chunk done -> the slot goes live with its first token
        self._admitting = None
        resume = req._resume is not None
        if self.page_size and not resume:
            self._insert_prefix(req, slot)
        if resume:
            # the replayed slot continues where it was paged out: last
            # emitted token, restored chain, no new emission
            first_tok = int(req.tokens[-1])
            length = int(seq.size)
            req._resume = None
            self.resumptions += 1
        else:
            first_tok = int(jax.device_get(first))
            length = int(req.prompt.size)
        self._tokens, self._lengths, self._rngs = self._admit_state(
            self._tokens, self._lengths, self._rngs, slot, first_tok, length,
            decode_rng,
        )
        req.slot = slot
        req.prefill_kernel = "dense"
        self._slot_req[slot] = req
        self._active[slot] = True
        if resume:
            # the paged-out + requeued + replay wait is scheduling latency
            # (the record's preemptions field owns it), not an inter-token
            # gap: clearing the reference clock makes the first post-resume
            # token gap-less, so one preemption cannot fake an ITL-p99
            # breach and trip the AIMD controller into cutting the budget
            req._last_token_t = 0.0
            return True
        now = time.perf_counter()
        req.first_token_t = now
        if tr is not None:
            tr.on_first_token(req, now - req.submit_t)
        # _last_token_t stays 0.0 until _emit sets it: the first token has
        # no preceding token, so it must not record a spurious 0.0 ITL gap
        self._emit(req, first_tok, now)
        return True

    def _ragged_advance(self, tr) -> bool:
        """One packed ragged-prefill dispatch: the primary admission's
        next tail segment plus — when capacity remains — the WHOLE tails
        of further queued requests, packed token-block-aligned into the
        smallest compiled grid capacity that fits. Replaces the per-slot
        bucket chunks of the dense path (which stays compiled as the
        fallback and bit-exactness oracle); preserves the interleave
        discipline (one dispatch per scheduler iteration) and the
        zero-recompile invariant (grid capacities fixed at warmup)."""
        req, slot, plan, idx, prefill_rng, decode_rng, seq = self._admitting
        bt = self._ragged_bt
        cap_max = self._ragged_caps[-1]
        # ``idx`` is repurposed by this path as the next global position
        # to prefill (0 = nothing dispatched yet -> start past the
        # prefix hit the admit plan recorded; a first dispatch always
        # advances past position 0, so the sentinel is unambiguous)
        cur = plan[0][0] if idx == 0 else idx
        n = min(seq.size - cur, cap_max)
        if self._faults is not None:
            self._faults.before_prefill(self)
        try:
            self._ensure_writable(req, slot, cur, cur + n - 1)
        except PagePressure:
            # same ladder as the chunked dispatch: page out a strictly
            # lower-priority victim before shedding the admission
            resolved = self._relieve_pressure(req, slot)
            if resolved:
                try:
                    self._ensure_writable(req, slot, cur, cur + n - 1)
                except PagePressure:
                    resolved = False
            if not resolved:
                self._abort_admission(
                    time.perf_counter(), "shed", SHED_PAGE_EXHAUSTED
                )
                flight = getattr(self.telemetry, "flight", None)
                if flight is not None:
                    flight.note("request_shed", request_id=req.id,
                                reason=SHED_PAGE_EXHAUSTED)
                return True
        # packs: [request, slot, s0, s1, prefill_rng, decode_rng, seq,
        # primary]. The primary may be mid-tail (longer than the largest
        # grid); co-admitted tails are always whole, so every co-admit
        # completes in-dispatch and the admission singleton invariant
        # (_reap/_abort only ever see self._admitting[0]) holds.
        packs = [[req, slot, cur, cur + n, prefill_rng, decode_rng, seq,
                  True]]
        used = -(-n // bt) * bt
        # co-admission: pull further queued requests into the same grid.
        # FIFO only (a scheduler's WFQ/priority pick must stay one-at-a-
        # time so its accounting observes each admission), no KV tiers
        # (a tier probe can stage a restore, which needs the singleton),
        # and a conservative no-hit fit check — a prefix hit only ever
        # shrinks the tail, so fitting cold guarantees fitting planned.
        if self._sched is None and self._tiers is None:
            while self._free and self._queue and used + bt <= cap_max:
                nxt = self._queue[0]
                if nxt.done:
                    self._queue.popleft()
                    continue
                if nxt._resume is not None:
                    # resumes restore a saved RNG chain and emit nothing;
                    # they admit alone through the singleton path
                    break
                if used + -(-int(nxt.prompt.size) // bt) * bt > cap_max:
                    break
                self._queue.popleft()
                slot2 = self._free.pop()
                p_rng, d_rng = jax.random.split(nxt.rng)
                plan2 = self._paged_admit_plan(nxt, slot2, nxt.prompt)
                hit2 = plan2[0][0]
                n2 = int(nxt.prompt.size) - hit2
                try:
                    self._ensure_writable(nxt, slot2, hit2, hit2 + n2 - 1)
                except PagePressure:
                    # back out this co-admission and requeue at the head:
                    # it re-admits alone next iteration, where the full
                    # relieve/shed pressure ladder applies
                    self._release_slot_pages(slot2, nxt.tenant)
                    self._free.append(slot2)
                    if nxt.prefix_hit:
                        self.kv_tier_hits["hbm"] -= 1
                        nxt.prefix_hit = 0
                    self._queue.appendleft(nxt)
                    break
                if tr is not None:
                    tr.on_admission(
                        nxt, slot2, time.perf_counter() - nxt.submit_t
                    )
                packs.append([nxt, slot2, hit2, hit2 + n2, p_rng, d_rng,
                              nxt.prompt, False])
                used += -(-n2 // bt) * bt
        rcap = next(c for c in self._ragged_caps if c >= used)
        ids = np.zeros((1, rcap), np.int32)
        row_slot = np.full((rcap,), -1, np.int32)
        row_pos = np.full((rcap,), -1, np.int32)
        hist = np.zeros((self.num_slots,), np.int32)
        last_rows = np.zeros((self.num_slots,), np.int32)
        rngs = np.zeros((self.num_slots, 2), np.uint32)
        fresh = attended = read_tok = 0
        ps = self.page_size
        r = 0
        for preq, psl, s0, s1, prng, _, pseq, _ in packs:
            nseg = s1 - s0
            nb = -(-nseg // bt)
            ids[0, r:r + nseg] = pseq[s0:s1]
            # pad rows of a pack's LAST block keep the slot id (the
            # kernel reads the block's first row to name its slot; pads
            # are dead through pos = -1, not slot = -1)
            row_slot[r:r + nb * bt] = psl
            row_pos[r:r + nseg] = np.arange(s0, s1)
            hist[psl] = s0
            last_rows[psl] = r + nseg - 1
            rngs[psl] = np.asarray(jax.device_get(prng), np.uint32)
            r += nb * bt
            # host-side roofline counts for the dynamic cost row: causal
            # qk pairs actually attended, and kv tokens streamed (each
            # token block walks the slot's prefix pages plus the packed
            # fresh blocks at or below it)
            fresh += nseg
            attended += (s1 * (s1 + 1) - s0 * (s0 + 1)) // 2
            read_tok += nb * (-(-s0 // ps) * ps) + bt * nb * (nb + 1) // 2
        ids_dev = jnp.asarray(ids)
        self._note_forensics(f"ragged_prefill_{rcap}", {"ids": ids_dev})
        t0 = time.perf_counter()
        self._arena, firsts = self._ragged_prefill_fn(rcap)(
            self.params, self._arena, ids_dev, jnp.asarray(row_slot),
            jnp.asarray(row_pos), jnp.asarray(hist), self._page_tables,
            jnp.asarray(last_rows), jnp.asarray(rngs),
        )
        firsts_h = np.asarray(jax.device_get(firsts))
        wall = time.perf_counter() - t0
        costs = (getattr(self.telemetry, "costs", None)
                 if self.telemetry is not None else None)
        if costs is not None:
            costs.note_wall(f"ragged_prefill_{rcap}", wall)
            costs.note_dynamic(
                "ragged_prefill_kernel", wall,
                flops=float(self._kernel_flops_per_token * attended),
                hbm_bytes=float(self._kv_token_bytes * (read_tok + fresh)),
                calls=1,
            )
        usage = self._usage()
        self.prefill_packed_tokens += fresh
        self._prefill_tokens_dispatched += fresh
        self._prefill_rows_dispatched += rcap
        now = time.perf_counter()
        for preq, psl, s0, s1, prng, drng, pseq, primary in packs:
            if tr is not None:
                tr.on_prefill_chunk(preq, psl, s0, s1 - s0, t0, wall)
            if usage is not None:
                usage.note_prefill(preq.tenant, s1 - s0)
                # the shared dispatch wall is billed proportionally to
                # each tenant's live tokens in the pack
                usage.note_compute(
                    preq.tenant, wall * 1e3 * (s1 - s0) / max(fresh, 1)
                )
            if primary and s1 < pseq.size:
                # mid-tail: the primary stays the admission singleton
                # and resumes at position s1 next scheduler iteration
                # (a mid-tail primary fills the whole grid, so it never
                # coexists with co-admits)
                self._admitting[3] = s1
                continue
            if primary:
                self._admitting = None
            resume = preq._resume is not None
            if not resume:
                self._insert_prefix(preq, psl)
            if resume:
                # the replayed slot continues where it was paged out:
                # last emitted token, restored chain, no new emission
                first_tok = int(preq.tokens[-1])
                length = int(pseq.size)
                preq._resume = None
                self.resumptions += 1
            else:
                first_tok = int(firsts_h[psl])
                length = int(preq.prompt.size)
            self._tokens, self._lengths, self._rngs = self._admit_state(
                self._tokens, self._lengths, self._rngs, psl, first_tok,
                length, drng,
            )
            preq.slot = psl
            preq.prefill_kernel = "ragged"
            self._slot_req[psl] = preq
            self._active[psl] = True
            if resume:
                preq._last_token_t = 0.0
                continue
            preq.first_token_t = now
            if tr is not None:
                tr.on_first_token(preq, now - preq.submit_t)
            self._emit(preq, first_tok, now)
        return True

    def _burst_len(self) -> int:
        """steps_per_call when a fused burst cannot delay an admission or
        overshoot any request's token budget, else 1. Only these two values
        ever compile, keeping the program set bounded."""
        k = self.steps_per_call
        if k <= 1 or self._admitting is not None or (self._queued_depth() and self._free):
            return 1
        remaining = min(
            req.max_new_tokens - len(req.tokens) for req in self._slot_req.values()
        )
        return k if remaining >= k else 1

    def _next_write_pos(self, req: Request) -> int:
        """The slot's next cache write position: the latest emitted token's
        K/V has not been written yet (prefill samples the first token, each
        decode step writes the PREVIOUS token before sampling the next)."""
        return req.prompt.size + len(req.tokens) - 1

    def _kernel_step_cost(self, steps: int, width: int, extra: int = 0) -> dict:
        """Modeled cost of the paged decode kernel for ``steps`` fused
        dispatches of query width ``width`` over the current live slots
        (``extra`` = additional positions written past the frontier this
        round: k-1 for a burst, K for a verify). Token count is page-
        rounded per slot — exactly the pages the kernel walks — so the
        roofline row's achieved bytes/s tracks LIVE tokens, while the
        static ``decode_step`` row keeps billing the arena-shaped program
        (the gap between the two is the kernel's win, made attributable)."""
        ps = self.page_size
        toks = 0
        for req in self._slot_req.values():
            pos = self._next_write_pos(req) + extra
            toks += (pos // ps + 1) * ps
        return {
            "flops": float(self._kernel_flops_per_token * toks * steps * width),
            "hbm_bytes": float(self._kv_token_bytes * toks * steps),
            "calls": steps,
        }

    def _spec_verify_once(self) -> bool:
        """One speculative round: host drafter proposes K tokens per slot,
        one batched verify dispatch checks them all, the longest accepted
        prefix (plus the bonus sample) is emitted. Replaces the burst when
        spec is on — both amortize the host round trip, but verify turns
        the decode step's idle MXU into accepted tokens."""
        k = self.spec_k
        drafts = np.zeros((self.num_slots, k), np.int32)
        # a drafter exposing `lookback` only reads that many trailing
        # tokens, so build just the context tail — rebuilding the full
        # prompt+generation history every round is O(T^2) over a generation
        lb = int(getattr(self._drafter, "lookback", 0) or 0)
        for slot, req in list(self._slot_req.items()):
            if slot not in self._slot_req:
                continue  # shed/preempted while relieving another slot
            gen = np.asarray(req.tokens[-lb:] if lb else req.tokens, np.int32)
            if lb and gen.size >= lb:
                ctx = gen
            else:
                head = req.prompt[-(lb - gen.size):] if lb else req.prompt
                ctx = np.concatenate([np.asarray(head, np.int32), gen])
            drafts[slot] = self._drafter.propose(ctx, k)
            pos = self._next_write_pos(req)
            if not self._grow_or_resolve(req, slot, pos, pos + k):
                continue
        if not self._slot_req:
            return True  # every live slot was shed under page pressure
        kernel_cost = (
            self._kernel_step_cost(1, k + 1, extra=k)
            if self._kernel_costed_verify else None
        )
        drafts_dev = jnp.asarray(drafts)
        self._note_forensics(
            "spec_verify",
            {"tokens": self._tokens, "drafts": drafts_dev,
             "lengths": self._lengths, "active": self._active,
             "rngs": self._rngs},
        )
        t0 = time.perf_counter()
        (self._arena, self._tokens, self._lengths, self._rngs, cand, m) = (
            self._verify_step(
                self.params, self._arena, self._tokens, drafts_dev,
                self._lengths, self._active, self._rngs, self._page_tables,
            )
        )
        cand_h = np.asarray(jax.device_get(cand))  # [N, K+1]; forces the step
        m_h = np.asarray(jax.device_get(m))
        now = time.perf_counter()
        wall = now - t0
        self.step_count += 1
        self._usage_note_step(wall)
        emitted = 0
        for slot, req in list(self._slot_req.items()):
            accepted = int(m_h[slot])
            n_emit = accepted + 1
            req.spec_proposed += k
            req.spec_accepted += accepted
            self.spec_proposed += k
            self.spec_accepted += accepted
            for i in range(n_emit):
                # amortize the verify wall across this slot's emitted run
                # (same reasoning as the fused-burst ITL amortization)
                self._emit(req, int(cand_h[slot, i]), t0 + wall * (i + 1) / n_emit)
                emitted += 1
                if req.done:
                    break  # budget/eos hit mid-run: drop the rest
        self._step_samples.append((wall, emitted, 1))
        if self.telemetry is not None:
            self.telemetry.on_step(self, wall, tokens=emitted, steps=1)
            costs = getattr(self.telemetry, "costs", None)
            if costs is not None:
                costs.note_wall("spec_verify", wall)
                if kernel_cost is not None:
                    costs.note_dynamic("paged_decode_kernel", wall, **kernel_cost)
        return True

    def _grow_or_resolve(self, req: Request, slot: int, lo: int, hi: int) -> bool:
        """Grow a live slot's pages for the next write range, resolving
        page pressure by preempting a strictly-lower-priority victim (its
        pages move here) or, when none qualifies, shedding ``req`` itself
        — the one request outgrowing capacity pays, the loop never
        raises. True when the slot is still live and writable."""
        while True:
            try:
                self._ensure_writable(req, slot, lo, hi)
                return True
            except PagePressure:
                if self._relieve_pressure(req, slot):
                    continue
                req.shed_reason = SHED_PAGE_EXHAUSTED
                self._terminate(req, time.perf_counter(), "shed", "shed")
                flight = getattr(self.telemetry, "flight", None)
                if flight is not None:
                    flight.note("request_shed", request_id=req.id,
                                reason=SHED_PAGE_EXHAUSTED)
                return False

    def _decode_once(self) -> bool:
        if not self._slot_req:
            return False
        if self.spec_k:
            return self._spec_verify_once()
        k = self._burst_len()
        if self.page_size:
            for slot, req in list(self._slot_req.items()):
                if slot not in self._slot_req:
                    continue  # shed/preempted while relieving another slot
                pos = self._next_write_pos(req)
                self._grow_or_resolve(req, slot, pos, pos + k - 1)
            if not self._slot_req:
                return True  # every live slot was shed under page pressure
        if self._faults is not None:
            self._faults.before_decode(self)
        # snapshot BEFORE dispatch/emission: finished requests leave
        # _slot_req during _emit, but their pages were walked this round
        kernel_cost = (
            self._kernel_step_cost(k, 1, extra=k - 1)
            if self._kernel_costed else None
        )
        self._note_forensics(
            "decode_step" if k == 1 else f"decode_burst{k}",
            {"tokens": self._tokens, "lengths": self._lengths,
             "active": self._active, "rngs": self._rngs},
        )
        step_extra = (self._page_tables,) if self.page_size else ()
        t0 = time.perf_counter()
        if k > 1:
            self._arena, self._tokens, self._lengths, self._rngs, toks = (
                self._decode_burst(k)(
                    self.params, self._arena, self._tokens, self._lengths,
                    self._active, self._rngs, *step_extra,
                )
            )
            host = np.asarray(jax.device_get(toks))  # [K, N]; forces the burst
        else:
            self._arena, self._tokens, self._lengths, self._rngs = self._decode_step(
                self.params, self._arena, self._tokens, self._lengths, self._active,
                self._rngs, *step_extra,
            )
            host = np.asarray(jax.device_get(self._tokens))[None]  # [1, N]
        now = time.perf_counter()
        wall = now - t0
        self.step_count += k
        self._usage_note_step(wall)
        emitted = 0
        for i in range(k):
            # a fused burst delivers k tokens in one host RTT; amortize the
            # burst wall across them so ITL samples measure the chip's
            # per-token pace instead of k-1 zeros plus one k-sized spike
            # (the gaps feeding both the engine deque and the serving/itl
            # SLO histogram — and through it the p99 profiler trigger)
            ts = t0 + wall * (i + 1) / k
            for slot, req in list(self._slot_req.items()):
                self._emit(req, int(host[i, slot]), ts)
                emitted += 1
        # count DELIVERED tokens, not n_active*k: an eos finish mid-burst
        # drops its slot's remaining burst tokens, and tokens/s must not
        # claim them
        self._step_samples.append((wall, emitted, k))
        if self.telemetry is not None:
            self.telemetry.on_step(self, wall, tokens=emitted, steps=k)
            costs = getattr(self.telemetry, "costs", None)
            if costs is not None:
                # a fused burst is a lax.scan of k step BODIES, so its wall
                # bills the captured decode_step program as k executions —
                # the roofline row keeps accumulating in burst mode instead
                # of splitting into an uncaptured decode_burst<k> row
                costs.note_wall("decode_step", wall, calls=k)
                if kernel_cost is not None:
                    costs.note_dynamic("paged_decode_kernel", wall, **kernel_cost)
        return True

    def _usage_note_step(self, wall_s: float):
        """Attribute one batched decode/verify dispatch's wall across the
        live slots' tenants, evenly — called BEFORE emission (finished
        requests leave ``_slot_req`` during ``_emit``, but they rode this
        dispatch)."""
        usage = self._usage()
        if usage is None or not self._slot_req:
            return
        share = wall_s * 1e3 / len(self._slot_req)
        for req in self._slot_req.values():
            usage.note_compute(req.tenant, share)

    def _emit(self, req: Request, token: int, now: float):
        req.tokens.append(token)
        self.generated_tokens += 1
        if self._sched is not None:
            self._sched.note_tokens(req.tenant, 1)
        usage = self._usage()
        if usage is not None:
            # the conservation law: per-tenant decode tokens sum exactly
            # to generated_tokens — both increment here and only here
            usage.note_decode(req.tenant)
        gap = (now - req._last_token_t) if req._last_token_t else None
        if gap is not None:
            self._itl.append(gap)
            self._itl_emitted += 1
            tr = self._tracer()
            if tr is not None:
                tr.on_token(req, gap, len(req.tokens) - 1)
        req._last_token_t = now
        if req.on_token is not None:
            try:
                req.on_token(token, req)
            except Exception:
                # a poisoned request (raising downstream consumer) must
                # cost exactly one request, never the serving loop
                self._terminate(req, now, "cancelled", "callback_error")
                return
        if self.eos_token_id is not None and token == self.eos_token_id:
            self._finish(req, now, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(req, now, "budget")

    def _finish(self, req: Request, now: float, reason: str = "budget"):
        self._terminate(req, now, "finished", reason)

    # -- metrics -----------------------------------------------------------

    def mark_steady(self):
        """Snapshot the compile counters: every compile AFTER this call
        counts as an admission recompile (the invariant says there are
        none). Call once the engine has seen each prefill bucket + the
        decode step — e.g. after a warmup wave."""
        self._steady_mark = self._counters()

    @property
    def admission_recompiles(self) -> Optional[int]:
        """Backend compiles since :meth:`mark_steady` (None before it)."""
        if self._steady_mark is None:
            return None
        return self._counters()["count"] - self._steady_mark["count"]

    def executable_memory_stats(self, cached_only: bool = False) -> dict:
        """``memory_analysis`` of the live fused decode step — argument /
        output / temp / generated-code bytes, the flight-recorder bundle's
        "what was the compiled program actually holding" section. Computed
        ON THE ENGINE THREAD (at ``warmup()``, or the first direct call)
        and cached: a flight dump passes ``cached_only=True`` because its
        caller may be the watchdog thread diagnosing a WEDGED backend, and
        a fresh lower+compile there would hang exactly when the evidence
        matters. Backends without memory_analysis report {}."""
        if self._exe_mem is not None or cached_only:
            return self._exe_mem or {}
        try:
            step_extra = (self._page_tables,) if self.page_size else ()
            compiled = self._decode_step.lower(
                self.params, self._arena, self._tokens, self._lengths,
                self._active, self._rngs, *step_extra,
            ).compile()
            costs = getattr(self.telemetry, "costs", None)
            if costs is not None:
                # same AOT object feeds the roofline registry: the fused
                # decode step is almost always the memory-bound poster
                # child (per-token HBM traffic ~= whole KV arena + params)
                costs.capture("decode_step", compiled)
            ma = compiled.memory_analysis()
            out = {}
            for key in ("argument_size_in_bytes", "output_size_in_bytes",
                        "temp_size_in_bytes", "generated_code_size_in_bytes"):
                v = getattr(ma, key, None)
                if isinstance(v, (int, float)):
                    out[key] = int(v)
            self._exe_mem = out
        except Exception:
            self._exe_mem = {}
        return self._exe_mem

    def metrics(self) -> dict:
        """Serving gauges, ``serving/``-namespaced for the telemetry rollup
        (TelemetrySession.attach_serving feeds these into every flush)."""
        out = {
            "serving/queue_depth": self._queued_depth(),
            "serving/slot_occupancy": len(self._slot_req) / self.num_slots,
            "serving/requests_completed": self.requests_completed,
            "serving/generated_tokens": self.generated_tokens,
            "serving/arena_bytes": self.arena_bytes,
            # storage bits per K/V value (16 = unquantized) — the capacity
            # dashboards read this beside arena_bytes/pages_total to tell
            # a quantized arena from a shrunk one
            "serving/kv_cache_bits": kv_cache_bits(self.kv_cache_dtype),
        }
        if (
            self._sched is not None
            or self.requests_shed or self.requests_cancelled or self.preemptions
        ):
            out["serving/shed"] = self.requests_shed
            out["serving/cancelled"] = self.requests_cancelled
            out["serving/preemptions"] = self.preemptions
            out["serving/resumptions"] = self.resumptions
        if self._sched is not None:
            out.update(self._sched.metrics())
        if self._controller is not None:
            out["serving/itl_budget"] = round(self._controller.budget, 4)
            out["serving/itl_slo_breaches"] = self._controller.breaches
            out["serving/itl_budget_adjustments"] = self._controller.adjustments
        if self._draining:
            out["serving/draining"] = True
        if self._step_samples:
            wall = sum(w for w, _, _ in self._step_samples)
            toks = sum(n for _, n, _ in self._step_samples)
            if wall > 0:
                out["serving/tokens_per_s"] = toks / wall
            out["serving/decode_step_ms_p50"] = 1e3 * float(
                np.median([w / s for w, _, s in self._step_samples])
            )
        # the terminal-outcome denominator the shed-rate burn alert
        # divides by (telemetry/alerts.py): every request that reached an
        # outcome, whatever it was
        out["serving/requests_terminal"] = (
            self.requests_completed + self.requests_shed
            + self.requests_cancelled
        )
        if self._itl:
            itl = np.asarray(self._itl)
            out["serving/itl_p50_ms"] = 1e3 * float(np.percentile(itl, 50))
            out["serving/itl_p95_ms"] = 1e3 * float(np.percentile(itl, 95))
            # recent-window p99 (same observation the AIMD controller
            # acts on): the live gauge the ITL burn-rate alert samples —
            # unlike the lifetime histogram p99, it decays once the
            # regression clears
            p99, _ = self._recent_itl_p99_ms()
            if p99 is not None:
                out["serving/itl_recent_p99_ms"] = round(p99, 3)
        if self.page_size:
            out["serving/pages_in_use"] = self._allocator.in_use
            out["serving/pages_total"] = self.num_pages
            out["serving/page_size"] = self.page_size
            out["serving/page_forks"] = self.page_forks
            out["serving/decode_kernel_active"] = bool(self._kernel_costed)
            out["serving/prefill_kernel_active"] = bool(self._ragged_prefill)
            out["serving/prefill_packed_tokens"] = int(
                self.prefill_packed_tokens
            )
            if self.kv_pages_exported or self.kv_pages_imported:
                out["serving/kv_pages_exported"] = self.kv_pages_exported
                out["serving/kv_pages_imported"] = self.kv_pages_imported
            if self._prefix is not None:
                out["serving/prefix_hit_ratio"] = self._prefix.hit_ratio
                out["serving/prefix_hit_tokens"] = self._prefix.hit_tokens
                out["serving/prefix_entries"] = len(self._prefix.entries)
                out["serving/prefill_chunks_skipped"] = self.prefill_chunks_skipped
                if self._prefix.ghost is not None:
                    # ghost-cache economics: the hit ratio the prefix
                    # cache WOULD have at 2x/4x/10x entry capacity, plus
                    # reuse-after-evict distances — the evidence base for
                    # a host/disk KV tier (ROADMAP item 2)
                    out.update(self._prefix.ghost.gauges())
            if self._tiers is not None:
                out.update(self._tiers.gauges())
                lookups = self._prefix.lookups if self._prefix else 0
                for tier, hits in self.kv_tier_hits.items():
                    out[f"serving/kv_tier_hits_{tier}"] = hits
                    out[f"serving/kv_tier_hit_ratio_{tier}"] = (
                        hits / lookups if lookups else 0.0
                    )
                out["serving/kv_restores"] = self.kv_restores
                out["serving/kv_restores_aborted"] = self.kv_restores_aborted
                out["serving/kv_restore_batches"] = self.kv_restore_batches
                out["serving/kv_restore_overlap_frac"] = (
                    self.kv_restore_batches_overlapped / self.kv_restore_batches
                    if self.kv_restore_batches else 0.0
                )
        if self._prefill_rows_dispatched:
            # fraction of dispatched prefill rows that were padding —
            # both paths dispatch fixed row counts (chunk buckets or
            # ragged grid capacities), so the gauge compares them
            # directly; the ragged packer's win is this number falling
            out["serving/prefill_pad_waste_frac"] = (
                1.0 - self._prefill_tokens_dispatched
                / self._prefill_rows_dispatched
            )
        if self.spec_k:
            out["serving/spec_proposed"] = self.spec_proposed
            out["serving/spec_accepted"] = self.spec_accepted
            out["serving/spec_accept_rate"] = (
                self.spec_accepted / self.spec_proposed if self.spec_proposed
                else 0.0
            )
        if self._steady_mark is not None:
            out["serving/admission_recompiles"] = self.admission_recompiles
        # the placement-signal contract (telemetry/fleet.py, documented in
        # docs/telemetry.md "Fleet view"): one comparable scalar a router
        # ranks replicas by, plus the raw components it folds — exported
        # by EVERY engine, flat or paged, scheduler or not
        from ..telemetry.fleet import load_score

        out["serving/num_slots"] = self.num_slots
        out["serving/free_slots"] = self.num_slots - len(self._slot_req)
        if self.page_size:
            out["serving/free_pages"] = self._allocator.free_count
        out["serving/load_score"] = load_score(
            queue_depth=out["serving/queue_depth"],
            num_slots=self.num_slots,
            slot_occupancy=out["serving/slot_occupancy"],
            free_pages=out.get("serving/free_pages"),
            pages_total=self.num_pages if self.page_size else None,
            itl_recent_p99_ms=out.get("serving/itl_recent_p99_ms"),
            itl_slo_ms=(
                self._sched.config.itl_slo_ms if self._sched is not None else None
            ),
            draining=self._draining,
        )
        # sustainable-rate estimate + headroom (telemetry/capacity.py):
        # the autoscaler's scale decision inputs, fed the serving gauges
        # above plus the roofline registry's decode-step attribution
        from ..telemetry.capacity import CapacityModel

        if self._capacity_model is None:
            self._capacity_model = CapacityModel()
        costs = getattr(self.telemetry, "costs", None)
        if costs is not None:
            cap_in = dict(out)
            cap_in.update(costs.rollup_keys(probe=False))
        else:
            cap_in = out
        out.update(self._capacity_model.observe(cap_in))
        return out

    @classmethod
    def from_dispatched(cls, dispatched, **kwargs):
        """Engine over a DispatchedModel (offloaded / quantized params +
        its in-graph placement transform) — the serving counterpart of
        ``generation.generate_dispatched``."""
        params = dispatched._concrete(dispatched.params)
        return cls(
            dispatched.definition, params,
            param_placer=dispatched.param_placer(), **kwargs,
        )


def _admit_state_fn(tokens, lengths, rngs, slot, first, length, rng):
    """Scatter one slot's go-live state (traced ``slot``: one compile total,
    not one per slot index)."""
    return (
        tokens.at[slot].set(first),
        lengths.at[slot].set(length),
        rngs.at[slot].set(rng),
    )


def generate_batched(
    definition,
    params,
    prompts,
    *,
    max_new_tokens: int = 32,
    num_slots: Optional[int] = None,
    seeds=None,
    **engine_kwargs,
):
    """One-shot batched generation: build a :class:`ServingEngine`, submit
    every prompt, run to completion. Returns a list of [prompt + generated]
    id arrays, token-exact vs. per-prompt ``generate()`` with the same
    seeds. For a long-lived server keep an engine instead — this helper
    rebuilds (and recompiles) per call."""
    engine = ServingEngine(
        definition, params,
        num_slots=num_slots or min(max(len(prompts), 1), 8),
        **engine_kwargs,
    )
    return engine.generate_batched(prompts, max_new_tokens=max_new_tokens, seeds=seeds)
