"""Hierarchical KV tiering: demote-on-evict prefix store under the HBM
prefix cache — host RAM → disk blobs → fleet peers.

PR 16's ghost-cache economics measured the gap this module closes: on
the canonical workload the 4x capacity shadow hits well above the real
cache, so a third of prefix misses are pure capacity misses recomputed
at full prefill cost. Instead of dropping an evicted
:class:`~.pages.PrefixEntry`'s pages, the engine demotes the entry's KV
down a tier and a later admission restores it — prefill compute (the
TTFT budget) traded for cheap PCIe/disk/DCN bytes.

The storage format at every tier is the **KV handoff blob** (PR 13,
``ServingEngine.export_prefix_kv``): per-leaf page arrays in arena
flatten order, payload AND scale leaves alike (same rank by design, so
quantized pages ride every tier untouched). The disk tier is literally
a handoff-to-yourself (the wire dict serialized to JSON, plus a
checksum so a torn write is rejected, never installed); the peer tier
rides the existing ``/v1/kv/export`` wire — replicas advertise a digest
directory (``/v1/kv/directory``) and a miss pulls a warm prefix from a
peer instead of recomputing it.

Tier probe order is **longest-prefix-first across all tiers**: for each
page-aligned candidate length, descending, the store checks host, then
disk, then the peer directories — the first hit wins, so a shorter hit
in a fast tier never shadows a longer one in a slow tier.

Everything here is host-side bookkeeping over numpy arrays and JSON —
no jax/flax (declared in ``analysis/hygiene.py``, locked by
tests/test_imports.py). The device work (page gather on demote, the
warmup-compiled ``install_page`` writes on restore) stays in the
engine, which owns the zero-recompile invariant.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from .pages import _digest

# tier names, probe order (hbm is the PrefixCache itself; this module
# owns the three below it)
TIERS = ("hbm", "host", "disk", "peer")

BLOB_SUFFIX = ".kvblob.json"


@dataclass
class TierConfig:
    """Capacity/wiring knobs for a :class:`TieredStore`.

    Capacities are **entry counts** (same unit as the prefix cache's
    ``max_entries``), so "host+disk = 4x the HBM cache" is a direct
    knob-to-knob statement and the ghost shadows can report headroom
    beyond the *total* (HBM+host+disk) capacity. Optional byte caps
    bound the actual RAM/disk footprint underneath. A tier with 0
    entries is disabled (a host-tier-only deployment just leaves
    ``disk_entries`` at 0)."""

    host_entries: int = 64
    disk_entries: int = 0
    disk_dir: Optional[str] = None
    host_bytes: Optional[int] = None
    disk_bytes: Optional[int] = None
    # pages installed per scheduler iteration on restore: the batch knob
    # that lets a restore overlap other slots' decode steps instead of
    # stalling the loop for the whole prefix
    restore_batch_pages: int = 4
    # ((name, base_url), ...) of peer replicas for the fleet tier
    peers: tuple = ()
    peer_ttl_s: float = 2.0

    def entry_capacity(self) -> int:
        """Entries the host+disk tiers can hold — what the ghost
        shadows add to the HBM cache's ``max_entries`` so their
        "would a bigger cache help?" answer measures headroom beyond
        the capacity that now exists."""
        return max(0, int(self.host_entries)) + max(0, int(self.disk_entries))


@dataclass
class TierEntry:
    """One demoted prefix, host-resident form: the handoff blob's
    payload as live numpy arrays (page axis = ``n_pages``), arena
    flatten order."""

    key: bytes                 # _digest(tokens)
    token_len: int
    tokens: np.ndarray         # int32 [token_len]
    n_pages: int
    arrays: list               # one np array per K/V leaf
    paths: list                # leaf identity (handoff wire paths)
    nbytes: int
    tenant: str = "default"
    last_used: int = 0
    _indexed: list = field(default_factory=list, repr=False)


def entry_nbytes(arrays, tokens) -> int:
    return int(sum(int(a.nbytes) for a in arrays) + int(tokens.nbytes))


def _page_axis(arr) -> int:
    # same rank convention as pages._KV_NDIM: page axis is ndim - 4
    return arr.ndim - 4


def slice_entry_pages(entry: TierEntry, token_len: int, page_size: int):
    """(tokens, arrays) for a page-aligned *prefix* of a stored entry —
    a longer demoted entry serves every shorter aligned prefix, so the
    tiers never store the same page twice across lengths."""
    n_pages = -(-token_len // page_size)
    if token_len == entry.token_len:
        return entry.tokens, entry.arrays
    arrays = [
        np.take(a, range(n_pages), axis=_page_axis(a)) for a in entry.arrays
    ]
    return entry.tokens[:token_len], arrays


def blob_checksum(doc: dict) -> str:
    """Content checksum over everything the install path will trust:
    header fields, tokens, and the raw leaf bytes — a torn or bit-
    flipped blob fails this before any page is written."""
    h = hashlib.blake2b(digest_size=16)
    h.update((
        f"{doc.get('version')}|{doc.get('page_size')}|"
        f"{doc.get('kv_cache_dtype')}|{doc.get('token_len')}|"
        f"{doc.get('n_pages')}|"
    ).encode())
    h.update(np.asarray(doc.get("tokens") or [], np.int32).tobytes())
    for leaf in doc.get("leaves") or []:
        h.update(
            f"{leaf.get('path')}|{leaf.get('dtype')}|{leaf.get('shape')}|".encode()
        )
        try:
            h.update(base64.b64decode(leaf.get("data") or ""))
        except (ValueError, TypeError):
            h.update(b"?")
    return h.hexdigest()


def entry_to_handoff(entry: TierEntry, *, page_size: int, kv_cache_dtype: str,
                     replica=None, token_len: Optional[int] = None) -> dict:
    """Serialize a tier entry (or an aligned prefix of it) to the PR 13
    handoff wire dict — THE serialization format of every tier."""
    length = entry.token_len if token_len is None else int(token_len)
    tokens, arrays = slice_entry_pages(entry, length, page_size)
    leaves = []
    for path, arr in zip(entry.paths, arrays):
        leaves.append({
            "path": path,
            "dtype": arr.dtype.name,
            "shape": list(arr.shape),
            "data": base64.b64encode(
                np.ascontiguousarray(arr).tobytes()
            ).decode("ascii"),
        })
    return {
        "version": 1,
        "page_size": int(page_size),
        "kv_cache_dtype": kv_cache_dtype,
        "token_len": int(length),
        "tokens": [int(t) for t in tokens],
        "n_pages": -(-length // page_size),
        "replica": replica,
        "leaves": leaves,
    }


def handoff_to_entry(doc: dict, tenant: str = "default") -> TierEntry:
    """Parse a handoff dict back into a host-resident entry (the disk
    tier's read path and the peer tier's pull). Raises ValueError on a
    malformed document; checksum verification is the caller's job (only
    disk blobs carry one)."""
    tokens = np.asarray(doc["tokens"], np.int32).reshape(-1)
    token_len = int(doc["token_len"])
    n_pages = int(doc["n_pages"])
    if tokens.size != token_len:
        raise ValueError("KV blob token accounting is inconsistent")
    arrays, paths = [], []
    for leaf in doc["leaves"]:
        arr = np.frombuffer(
            base64.b64decode(leaf["data"]), np.dtype(leaf["dtype"])
        ).reshape(leaf["shape"])
        if arr.ndim < 4 or arr.shape[_page_axis(arr)] != n_pages:
            raise ValueError(f"KV blob leaf {leaf.get('path')!r} page count "
                             "does not match n_pages")
        arrays.append(arr)
        paths.append(leaf["path"])
    if not arrays:
        raise ValueError("KV blob carries no leaves")
    return TierEntry(
        key=_digest(tokens), token_len=token_len, tokens=tokens,
        n_pages=n_pages, arrays=arrays, paths=paths,
        nbytes=entry_nbytes(arrays, tokens),
        tenant=str(doc.get("tenant") or tenant),
    )


def _http_json(base_url: str, path: str, payload=None, timeout_s: float = 5.0):
    """Minimal JSON-over-HTTP helper for the peer tier (GET when
    ``payload`` is None, POST otherwise). Returns the parsed body or
    None on any transport/decode/status failure — a peer pull is an
    optimization; its failure is a miss, never an exception."""
    import http.client
    from urllib.parse import urlsplit

    u = urlsplit(base_url)
    conn = http.client.HTTPConnection(
        u.hostname, u.port or 80, timeout=timeout_s
    )
    try:
        if payload is None:
            conn.request("GET", path)
        else:
            body = json.dumps(payload).encode()
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            return None
        return json.loads(data)
    except (OSError, ValueError):
        return None
    finally:
        conn.close()


class _LruIndex:
    """Shared host/disk bookkeeping: an entry table keyed by full-prefix
    digest plus a prefix index mapping every page-aligned prefix digest
    of every entry to ``(entry_key, prefix_len)`` — so one long demoted
    entry serves all its shorter aligned prefixes and the tiers never
    hold the same pages twice."""

    def __init__(self, max_entries: int, max_bytes: Optional[int]):
        self.max_entries = max(0, int(max_entries))
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.entries: dict = {}   # key -> TierEntry | disk stub dict
        self.index: dict = {}     # prefix digest -> {entry_key: prefix_len}
        self.nbytes = 0
        self._clock = 0

    def tick(self) -> int:
        self._clock += 1
        return self._clock

    def register(self, key: bytes, tokens: np.ndarray, token_len: int,
                 page_size: int) -> list:
        """Index every aligned prefix (+ the full length) of an entry;
        returns the (digest, key) pairs registered for later cleanup."""
        lengths = list(range(page_size, token_len + 1, page_size))
        if token_len % page_size:
            lengths.append(token_len)
        indexed = []
        for length in lengths:
            d = key if length == token_len else _digest(tokens[:length])
            self.index.setdefault(d, {})[key] = length
            indexed.append(d)
        return indexed

    def unregister(self, key: bytes, indexed: list):
        for d in indexed:
            slot = self.index.get(d)
            if slot is not None:
                slot.pop(key, None)
                if not slot:
                    del self.index[d]

    def probe(self, digest: bytes):
        """(entry_key, prefix_len) of any entry covering ``digest``,
        preferring the most recently used cover, or None."""
        slot = self.index.get(digest)
        if not slot:
            return None
        best = max(
            slot, key=lambda k: getattr(
                self.entries.get(k), "last_used",
                (self.entries.get(k) or {}).get("last_used", 0)
                if isinstance(self.entries.get(k), dict) else 0,
            ),
        )
        if best not in self.entries:
            return None
        return best, slot[best]

    def over_capacity(self) -> bool:
        if len(self.entries) > self.max_entries:
            return True
        return self.max_bytes is not None and self.nbytes > self.max_bytes

    def lru_key(self):
        if not self.entries:
            return None
        return min(
            self.entries, key=lambda k: (
                self.entries[k].last_used
                if isinstance(self.entries[k], TierEntry)
                else self.entries[k].get("last_used", 0)
            ),
        )


class TieredStore:
    """Host RAM → disk → peer prefix store behind the HBM prefix cache.

    ``put()`` is the demote-on-evict sink (HBM eviction feeds it); host
    overflow demotes the host LRU entry onward to disk; disk overflow
    deletes the disk LRU blob — eviction always cascades *down*, never
    sideways. ``probe()`` is the admission-side lookup, longest aligned
    prefix first across host → disk → peer directories. All byte
    movement reports through the ``on_bytes(tenant, tier, delta)`` hook
    (the usage accountant's byte-seconds meter) with the same symmetric
    contract as the engine's page hooks: every + has a matching −, so
    held bytes drain to exactly 0."""

    def __init__(self, config: TierConfig, *, page_size: int,
                 kv_cache_dtype: str = "bf16", replica=None,
                 on_bytes: Optional[Callable] = None,
                 fetch: Optional[Callable] = None,
                 clock=time.monotonic):
        self.config = config
        self.page_size = int(page_size)
        self.kv_cache_dtype = kv_cache_dtype or "bf16"
        self.replica = replica
        self.on_bytes = on_bytes
        self._fetch = fetch or _http_json
        self._clock = clock
        self.host = _LruIndex(config.host_entries, config.host_bytes)
        self.disk = _LruIndex(
            config.disk_entries if config.disk_dir else 0, config.disk_bytes
        )
        if config.disk_dir:
            os.makedirs(config.disk_dir, exist_ok=True)
            self._scan_disk()
        # peer directory cache: name -> (fetched_at, {digest_hex: token_len})
        self._peer_dirs: dict = {}
        # counters (engine merges these into serving/ metrics)
        self.demotions_host = 0
        self.demotions_disk = 0
        self.disk_corrupt_dropped = 0
        self.peer_pulls = 0
        self.peer_pull_failures = 0

    # -- byte accounting ----------------------------------------------------

    def _note_bytes(self, tenant: str, tier: str, delta: int):
        if self.on_bytes is not None and delta:
            self.on_bytes(tenant, tier, int(delta))

    # -- demotion sink (HBM -> host -> disk) --------------------------------

    def covers(self, key: bytes) -> bool:
        """Whether some tier entry already serves this exact prefix —
        the demote path's dedup check (re-demoting a prefix a longer
        entry already covers would store the same pages twice)."""
        return key in self.host.index or key in self.disk.index

    def put(self, entry: TierEntry):
        """Demote one evicted prefix into the host tier (cascading the
        host LRU victim to disk, and the disk LRU victim to oblivion,
        as capacity requires). No-op when the host tier is disabled or
        the prefix is already covered."""
        if self.host.max_entries <= 0 or entry.key in self.host.entries:
            return
        entry.last_used = self.host.tick()
        entry._indexed = self.host.register(
            entry.key, entry.tokens, entry.token_len, self.page_size
        )
        self.host.entries[entry.key] = entry
        self.host.nbytes += entry.nbytes
        self.demotions_host += 1
        self._note_bytes(entry.tenant, "host", entry.nbytes)
        while self.host.over_capacity():
            victim_key = self.host.lru_key()
            if victim_key is None:
                break
            victim = self.host.entries.pop(victim_key)
            self.host.unregister(victim_key, victim._indexed)
            self.host.nbytes -= victim.nbytes
            self._note_bytes(victim.tenant, "host", -victim.nbytes)
            self._demote_to_disk(victim)

    def _demote_to_disk(self, entry: TierEntry):
        if self.disk.max_entries <= 0 or entry.key in self.disk.entries:
            return
        doc = entry_to_handoff(
            entry, page_size=self.page_size,
            kv_cache_dtype=self.kv_cache_dtype, replica=self.replica,
        )
        doc["tenant"] = entry.tenant
        doc["checksum"] = blob_checksum(doc)
        path = os.path.join(
            self.config.disk_dir, entry.key.hex() + BLOB_SUFFIX
        )
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        nbytes = os.path.getsize(path)
        stub = {
            "path": path, "token_len": entry.token_len, "nbytes": nbytes,
            "tenant": entry.tenant, "last_used": self.disk.tick(),
            "indexed": self.disk.register(
                entry.key, entry.tokens, entry.token_len, self.page_size
            ),
        }
        self.disk.entries[entry.key] = stub
        self.disk.nbytes += nbytes
        self.demotions_disk += 1
        self._note_bytes(entry.tenant, "disk", nbytes)
        while self.disk.over_capacity():
            victim_key = self.disk.lru_key()
            if victim_key is None:
                break
            self._drop_disk(victim_key)

    def _drop_disk(self, key: bytes):
        stub = self.disk.entries.pop(key, None)
        if stub is None:
            return
        self.disk.unregister(key, stub["indexed"])
        self.disk.nbytes -= stub["nbytes"]
        self._note_bytes(stub["tenant"], "disk", -stub["nbytes"])
        try:
            os.unlink(stub["path"])
        except OSError:
            pass

    def _scan_disk(self):
        """Rebuild the disk index from blobs left by a previous process
        — a disk tier is durable storage, so a restarted replica serves
        session resumes across its own restart. Corrupt blobs found
        here are dropped and counted, same as on the probe path."""
        try:
            names = sorted(os.listdir(self.config.disk_dir))
        except OSError:
            return
        for name in names:
            if not name.endswith(BLOB_SUFFIX):
                continue
            path = os.path.join(self.config.disk_dir, name)
            doc = self._read_blob(path)
            if doc is None:
                continue
            try:
                tokens = np.asarray(doc["tokens"], np.int32).reshape(-1)
                token_len = int(doc["token_len"])
                key = _digest(tokens)
            except (KeyError, ValueError, TypeError):
                self._reject_blob(path)
                continue
            if key in self.disk.entries:
                continue
            nbytes = os.path.getsize(path)
            self.disk.entries[key] = {
                "path": path, "token_len": token_len, "nbytes": nbytes,
                "tenant": str(doc.get("tenant") or "default"),
                "last_used": self.disk.tick(),
                "indexed": self.disk.register(
                    key, tokens, token_len, self.page_size
                ),
            }
            self.disk.nbytes += nbytes
            self._note_bytes(
                str(doc.get("tenant") or "default"), "disk", nbytes
            )

    def _read_blob(self, path: str) -> Optional[dict]:
        """Parse + checksum-verify one disk blob; on ANY failure (torn
        write, truncation, bit flip, schema drift) the blob is deleted
        and counted — a corrupt page must never be installed."""
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            self._reject_blob(path)
            return None
        if not isinstance(doc, dict) or doc.get("version") != 1 \
                or int(doc.get("page_size") or 0) != self.page_size \
                or (doc.get("kv_cache_dtype") or "bf16") != self.kv_cache_dtype \
                or doc.get("checksum") != blob_checksum(doc):
            self._reject_blob(path)
            return None
        return doc

    def _reject_blob(self, path: str):
        self.disk_corrupt_dropped += 1
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- admission-side probe (host -> disk -> peer, longest first) ---------

    def _candidate_lengths(self, n: int, min_len: int) -> list:
        ps = self.page_size
        lengths = list(range(ps, n + 1, ps))
        if n % ps:
            lengths.append(n)
        return [length for length in sorted(lengths, reverse=True)
                if length > min_len]

    def probe(self, tokens: np.ndarray, limit: Optional[int] = None,
              min_len: int = 0) -> Optional[dict]:
        """Longest tier-resident prefix of ``tokens`` strictly longer
        than ``min_len`` (the HBM cache's own best — a tier restore
        shorter than what HBM already serves is pure waste). Returns
        ``{"tier", "token_len", "tokens", "arrays"}`` for host/disk
        hits, ``{"tier": "peer", "handoff": ...}`` for a peer pull, or
        None."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = int(tokens.size if limit is None else min(tokens.size, limit))
        memo: dict = {}

        def dig(length):
            d = memo.get(length)
            if d is None:
                d = memo[length] = _digest(tokens[:length])
            return d

        for length in self._candidate_lengths(n, min_len):
            d = dig(length)
            hit = self.host.probe(d)
            if hit is not None:
                entry = self.host.entries[hit[0]]
                entry.last_used = self.host.tick()
                toks, arrays = slice_entry_pages(entry, length, self.page_size)
                return {"tier": "host", "token_len": length,
                        "tokens": toks, "arrays": arrays,
                        "paths": entry.paths}
            hit = self.disk.probe(d)
            if hit is not None:
                got = self._restore_from_disk(hit[0], length)
                if got is not None:
                    return got
            got = self._pull_from_peer(d, tokens[:length], length)
            if got is not None:
                return got
        return None

    def _restore_from_disk(self, key: bytes, length: int) -> Optional[dict]:
        stub = self.disk.entries.get(key)
        if stub is None:
            return None
        doc = self._read_blob(stub["path"])
        if doc is None:
            # rejected (torn/corrupt): forget the stub so the probe
            # falls through to the peer tier / cold prefill
            stub = self.disk.entries.pop(key, None)
            if stub is not None:
                self.disk.unregister(key, stub["indexed"])
                self.disk.nbytes -= stub["nbytes"]
                self._note_bytes(stub["tenant"], "disk", -stub["nbytes"])
            return None
        try:
            entry = handoff_to_entry(doc)
        except (KeyError, ValueError, TypeError):
            self._reject_blob(stub["path"])
            self.disk.entries.pop(key, None)
            self.disk.unregister(key, stub["indexed"])
            self.disk.nbytes -= stub["nbytes"]
            self._note_bytes(stub["tenant"], "disk", -stub["nbytes"])
            return None
        stub["last_used"] = self.disk.tick()
        toks, arrays = slice_entry_pages(entry, length, self.page_size)
        return {"tier": "disk", "token_len": length, "tokens": toks,
                "arrays": arrays, "paths": entry.paths}

    # -- peer tier -----------------------------------------------------------

    def _peer_directory(self, name: str, url: str) -> dict:
        now = self._clock()
        cached = self._peer_dirs.get(name)
        if cached is not None and now - cached[0] < self.config.peer_ttl_s:
            return cached[1]
        doc = self._fetch(url, "/v1/kv/directory") or {}
        dirmap = {
            str(row.get("digest")): int(row.get("token_len") or 0)
            for row in (doc.get("prefixes") or [])
            if isinstance(row, dict)
        }
        self._peer_dirs[name] = (now, dirmap)
        return dirmap

    def _pull_from_peer(self, digest: bytes, tokens: np.ndarray,
                        length: int) -> Optional[dict]:
        if not self.config.peers:
            return None
        hexd = digest.hex()
        for name, url in self.config.peers:
            if hexd not in self._peer_directory(name, url):
                continue
            handoff = self._fetch(
                url, "/v1/kv/export", {"tokens": [int(t) for t in tokens]}
            )
            if not isinstance(handoff, dict) or not handoff.get("token_len"):
                # directory was stale (peer evicted since advertising):
                # count it and keep probing — the next length/peer may hit
                self.peer_pull_failures += 1
                continue
            self.peer_pulls += 1
            return {"tier": "peer", "token_len": int(handoff["token_len"]),
                    "handoff": handoff}
        return None

    # -- housekeeping --------------------------------------------------------

    def clear(self):
        """Drop every tier entry (bytes drain through the hook — the
        leak tests assert held bytes return to exactly 0)."""
        for key in list(self.host.entries):
            entry = self.host.entries.pop(key)
            self.host.unregister(key, entry._indexed)
            self.host.nbytes -= entry.nbytes
            self._note_bytes(entry.tenant, "host", -entry.nbytes)
        for key in list(self.disk.entries):
            self._drop_disk(key)
        self._peer_dirs.clear()

    def gauges(self) -> dict:
        """``serving/kv_*`` gauge fragment the engine merges into
        :meth:`~.engine.ServingEngine.metrics` (fleet merge policies in
        ``telemetry/fleet.py`` know each key's algebra)."""
        out = {
            "serving/kv_host_entries": len(self.host.entries),
            "serving/kv_host_bytes": self.host.nbytes,
            "serving/kv_demotions_host": self.demotions_host,
        }
        if self.config.disk_dir:
            out["serving/kv_disk_entries"] = len(self.disk.entries)
            out["serving/kv_disk_bytes"] = self.disk.nbytes
            out["serving/kv_demotions_disk"] = self.demotions_disk
            out["serving/kv_disk_corrupt_dropped"] = self.disk_corrupt_dropped
        if self.config.peers:
            out["serving/kv_peer_pulls"] = self.peer_pulls
            out["serving/kv_peer_pull_failures"] = self.peer_pull_failures
        return out
