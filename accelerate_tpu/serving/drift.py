"""KV-quantization drift harness: quantify what int8/int4 KV storage costs
in output quality, against the bf16 arena, on fixed seeds.

Two complementary measurements (docs/serving.md "Quantized KV cache"):

- **token-match rate** — run the SAME prompts/seeds through a bf16 engine
  and a quantized engine (greedy or sampled; both paths use the exact
  engine programs production serves with) and count position-wise token
  agreement over the generated continuations. This is the end-to-end
  number: it includes divergence cascades (one flipped argmax reroutes the
  rest of the stream), so it is the pessimistic bound a deployment should
  gate on.
- **teacher-forced logit error** — replay the bf16 continuation token by
  token through both cache precisions (prefill + scalar-index decode
  steps, the single-stream path) and compare the per-step logits: MSE and
  relative error vs the bf16 logits' own scale. Teacher forcing removes
  the cascade, so this isolates the per-step numeric cost of quantized
  storage — the number that should stay stable as generations get longer.

The harness is what the bench's ``kv_quant_token_match_rate`` row and the
tier-1 drift tests (tests/test_kv_quant.py) run; point it at a real model
via ``kv_quant_drift(definition, params, prompts, ...)`` when generation
quality looks degraded after enabling a quantized arena
(docs/troubleshooting.md has the recipe).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def _teacher_forced_logits(definition, params, tokens: np.ndarray,
                           n_prompt: int):
    """[steps, V] fp32 logits from prefill(prompt) + teacher-forced
    scalar-index decode steps over ``tokens[n_prompt:]`` — step i's row is
    the distribution the model holds BEFORE emitting tokens[n_prompt+i].
    Eager applies on purpose: the harness is a diagnostic, not a hot path,
    and skipping jit keeps it out of the compile counters a surrounding
    zero-recompile assertion may be watching."""
    import jax.numpy as jnp

    tokens = np.asarray(tokens, np.int32)
    steps = tokens.size - n_prompt
    out, mutated = definition.apply(
        {"params": params}, jnp.asarray(tokens[None, :n_prompt]),
        positions=jnp.arange(n_prompt), use_cache=True, mutable=["cache"],
    )
    logits = [out["logits"][0, -1]]
    cache = mutated["cache"]
    for i in range(steps - 1):
        pos = n_prompt + i
        out, mutated = definition.apply(
            {"params": params, "cache": cache},
            jnp.asarray(tokens[None, pos:pos + 1]),
            positions=jnp.asarray([pos]),
            use_cache=True, decode=True, mutable=["cache"],
        )
        cache = mutated["cache"]
        logits.append(out["logits"][0, -1])
    return np.stack([np.asarray(l, np.float32) for l in logits])


def kv_quant_drift(
    definition,
    params,
    prompts,
    *,
    kv_cache_dtype: str = "int8",
    max_new_tokens: int = 8,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    seeds=None,
    page_size: Optional[int] = None,
    num_slots: Optional[int] = None,
    max_cache_len: Optional[int] = None,
    prefill_chunks=None,
    logit_prompts: int = 2,
    baseline: Optional[dict] = None,
    **engine_kwargs,
) -> dict:
    """Compare a ``kv_cache_dtype`` KV arena against bf16 on ``prompts``
    (list of 1-D token-id arrays) with fixed ``seeds``. Returns::

        {
          "kv_cache_dtype": ..., "kv_cache_bits": ...,
          "token_match_rate":  position-wise continuation agreement in [0, 1],
          "exact_streams":     continuations that matched end to end,
          "sequences":         len(prompts),
          "tokens_compared":   total continuation positions,
          "logit_mse":         teacher-forced mean squared logit error,
          "logit_rel_err":     logit_mse / mean(bf16 logit^2),
          "arena_bytes_bf16" / "arena_bytes_quant" / "arena_bytes_ratio":
                               per-engine KV arena HBM (ratio = the slots-
                               per-chip multiplier at equal budget),
        }

    ``page_size`` selects the paged arena (what production serves);
    omitted, the flat slot arena is measured — drift is storage-precision
    math either way, and the tests assert flat == paged token-exactly.

    The result also carries a ``"baseline"`` dict (the bf16 streams +
    arena bytes). Pass it back via ``baseline=`` on a second call with
    the SAME prompts/seeds/engine shape to compare another
    ``kv_cache_dtype`` without rebuilding and re-running the bf16 engine
    — the bench compares int8 and int4 against one baseline this way.
    """
    from .engine import ServingEngine
    from .pages import kv_cache_bits

    prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
    if seeds is None:
        seeds = list(range(len(prompts)))
    n_slots = num_slots or min(max(len(prompts), 1), 4)
    need = max(p.size for p in prompts) + max_new_tokens
    cap = max_cache_len or -(-need // 16) * 16
    chunks = prefill_chunks or (min(16, cap // 2), min(64, cap))
    kw = dict(
        num_slots=n_slots, max_cache_len=cap,
        prefill_chunks=tuple(sorted(set(chunks))),
        temperature=temperature, top_k=top_k, **engine_kwargs,
    )
    if page_size:
        kw["page_size"] = page_size

    def run(kvq):
        engine = ServingEngine(definition, params, kv_cache_dtype=kvq, **kw)
        engine.telemetry = None
        streams = engine.generate_batched(
            prompts, max_new_tokens=max_new_tokens, seeds=seeds
        )
        bytes_ = engine.arena_bytes
        slots = engine.num_slots
        del engine
        return streams, bytes_, slots

    if baseline is None:
        base, base_bytes, slots = run("bf16")
        baseline = {
            "streams": base, "arena_bytes": base_bytes, "num_slots": slots,
        }
    else:
        base = baseline["streams"]
        base_bytes = baseline["arena_bytes"]
        slots = baseline["num_slots"]
    quant, quant_bytes, _ = run(kv_cache_dtype)

    matched = compared = exact = 0
    for p, a, b in zip(prompts, base, quant):
        ca, cb = np.asarray(a)[p.size:], np.asarray(b)[p.size:]
        matched += int(np.sum(ca == cb))
        compared += ca.size
        exact += int(np.array_equal(ca, cb))

    # teacher-forced logit error on the bf16 continuations (cascade-free)
    cfg = definition.config
    sized = dataclasses.replace(
        cfg, max_cache_len=cap, kv_cache_dtype="bf16",
        kv_page_size=None, kv_num_pages=None,
    )
    base_def = definition.clone(config=sized)
    quant_def = definition.clone(
        config=dataclasses.replace(sized, kv_cache_dtype=kv_cache_dtype)
    )
    sq_err = ref_sq = 0.0
    n_logits = 0
    for p, stream in list(zip(prompts, base))[:logit_prompts]:
        lb = _teacher_forced_logits(base_def, params, stream, p.size)
        lq = _teacher_forced_logits(quant_def, params, stream, p.size)
        sq_err += float(np.sum((lq - lb) ** 2))
        ref_sq += float(np.sum(lb ** 2))
        n_logits += lb.size
    logit_mse = sq_err / max(1, n_logits)
    return {
        "kv_cache_dtype": kv_cache_dtype,
        "kv_cache_bits": kv_cache_bits(kv_cache_dtype),
        "token_match_rate": matched / max(1, compared),
        "exact_streams": exact,
        "sequences": len(prompts),
        "tokens_compared": compared,
        "logit_mse": logit_mse,
        "logit_rel_err": logit_mse / max(1e-30, ref_sq / max(1, n_logits)),
        "arena_bytes_bf16": int(base_bytes),
        "arena_bytes_quant": int(quant_bytes),
        "arena_bytes_ratio": base_bytes / max(1, quant_bytes),
        "arena_bytes_per_slot_bf16": int(base_bytes) // slots,
        "arena_bytes_per_slot_quant": int(quant_bytes) // slots,
        "baseline": baseline,
    }
