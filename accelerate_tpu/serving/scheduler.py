"""SLO-aware multi-tenant scheduling policy for the serving engine.

``ServingEngine``'s original queue was a FIFO deque with a fixed
prefill/decode interleave: one tenant's 32k-token prefill storm freezes
every other tenant's inter-token latency, a burst past the slot/page
capacity raises out of ``step()``, and nothing closes the loop from the
ITL-p99 histograms the telemetry layer measures to a scheduling
decision. This module is the **policy layer** that fixes all three —
pure host-side bookkeeping the engine consults between dispatches:

- :class:`MultiTenantScheduler` — per-tenant **weighted-fair queues**
  (classic virtual-time WFQ: a tenant's virtual clock advances by
  ``cost / weight`` per scheduled request, the scheduler always picks
  the furthest-behind tenant), strict **priority classes** above the
  fair share (a higher class always schedules first; within a class,
  earliest ``deadline_s`` first), **token quotas** (a refilling token
  bucket per tenant; over-quota tenants only schedule when no in-quota
  tenant has work — work-conserving, so quotas bound *contended* share,
  not idle throughput), and **admission control**: bounded per-tenant
  and global queues whose overflow is a ``shed`` decision, not an
  exception, plus lowest-priority-first load shedding when queue depth
  or page pressure crosses a watermark.
- :class:`PrefillBudgetController` — the observe→act feedback loop for
  the ITL SLO: chunked prefill steals decode-step time from every live
  request, so the controller adapts **how many prefill chunks the
  engine may interleave per decode step** (multiplicative decrease when
  the observed ITL p99 breaches the SLO, additive increase while it
  holds) — closing the loop that ``profile_trigger_itl_p99_ms`` only
  observes.
- victim selection for **preemption** (:meth:`pick_victim`): when a
  higher-priority request waits and no slot is free, the engine pages
  out the lowest-priority, least-progressed victim (releasing its KV
  pages) and re-admits it later through the prefix cache.

Everything here is plain python/numpy and imports **without jax or
flax** (locked by tests/test_imports.py, like ``pages.py``): a router
tier can run the same admission/shed math on machines with no
accelerator stack.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

# terminal shed reasons (the `shed_reason` field on a shed Request and
# its JSONL record — bounded vocabulary so dashboards can group on it)
SHED_QUEUE_FULL = "queue_full"          # global queue watermark at submit
SHED_TENANT_QUEUE_FULL = "tenant_queue_full"
SHED_PAGE_PRESSURE = "page_pressure"    # watermark shed while queued
SHED_PAGE_EXHAUSTED = "page_exhausted"  # allocation failed mid-flight
SHED_DRAINING = "draining"              # engine refused/flushed on drain


@dataclass
class TenantConfig:
    """Static per-tenant policy. ``weight`` is the WFQ share; ``quota``
    is a token budget per ``quota_window_s`` (None = unmetered);
    ``max_queued`` bounds this tenant's queue (None = global bound
    only)."""

    weight: float = 1.0
    quota: Optional[float] = None
    max_queued: Optional[int] = None


@dataclass
class SchedulerConfig:
    """Knobs for :class:`MultiTenantScheduler` (docs/serving.md has the
    tuning guide)."""

    tenants: dict = field(default_factory=dict)  # name -> TenantConfig
    default_weight: float = 1.0
    max_queue_depth: int = 256           # global bound; submit past it sheds
    max_tenant_queue_depth: Optional[int] = 64  # default per-tenant bound
    quota_window_s: float = 1.0          # token buckets refill over this window
    # load shedding: when the paged arena's free fraction drops below the
    # watermark, the scheduler sheds the newest lowest-priority queued
    # request each step (queued work that cannot be admitted anyway)
    page_low_watermark: float = 0.05
    preemption: bool = True              # allow paging out lower-priority slots
    # bound on distinct tenant states (and the per-tenant gauge family):
    # rotating tenant ids reap the longest-idle unconfigured tenant
    # instead of growing the map forever (None = unbounded)
    max_tenants: Optional[int] = 4096
    # the ITL feedback loop (None = fixed 1-chunk-per-step interleave)
    itl_slo_ms: Optional[float] = None
    prefill_budget: float = 1.0          # starting chunks-per-decode-step
    prefill_budget_min: float = 0.25     # never starve admissions entirely
    prefill_budget_max: float = 4.0


class PrefillBudgetController:
    """Adapt the chunked-prefill budget to hold the ITL-p99 SLO.

    The budget is **prefill chunks per decode step** (fractional: 0.25
    means one chunk every 4th step). AIMD keeps it stable: a p99 breach
    multiplies the budget down (fast back-off protects the SLO), a
    comfortable margin adds a small step back up (slow recovery protects
    TTFT). ``observe()`` is fed the live recent-window p99 by the engine
    once per scheduler iteration; adjustments apply at most every
    ``observe_every`` observations so one noisy window cannot whipsaw
    the interleave.
    """

    def __init__(self, slo_ms: float, *, budget: float = 1.0,
                 min_budget: float = 0.25, max_budget: float = 4.0,
                 decrease: float = 0.7, increase: float = 0.1,
                 headroom: float = 0.8, observe_every: int = 8,
                 min_samples: int = 8):
        if slo_ms <= 0:
            raise ValueError(f"itl SLO must be positive, got {slo_ms}")
        if not (0 < min_budget <= budget <= max_budget):
            raise ValueError(
                f"need 0 < min <= budget <= max, got "
                f"{min_budget}/{budget}/{max_budget}"
            )
        if not (0 < decrease < 1):
            raise ValueError(f"decrease must be in (0, 1), got {decrease}")
        self.slo_ms = float(slo_ms)
        self.budget = float(budget)
        self.min_budget = float(min_budget)
        self.max_budget = float(max_budget)
        self.decrease = float(decrease)
        self.increase = float(increase)
        self.headroom = float(headroom)
        self.observe_every = max(1, int(observe_every))
        self.min_samples = max(1, int(min_samples))
        self.breaches = 0      # observations over the SLO (acted or not)
        self.adjustments = 0   # times the budget actually moved
        self._since_adjust = 0

    def observe(self, itl_p99_ms: Optional[float], samples: int = 0) -> float:
        """One control-loop tick: fold the live window's p99 in, return
        the (possibly adjusted) budget."""
        if itl_p99_ms is None or samples < self.min_samples:
            return self.budget
        over = itl_p99_ms > self.slo_ms
        if over:
            self.breaches += 1
        self._since_adjust += 1
        if self._since_adjust < self.observe_every:
            return self.budget
        self._since_adjust = 0
        if over:
            new = max(self.min_budget, self.budget * self.decrease)
        elif itl_p99_ms < self.headroom * self.slo_ms:
            new = min(self.max_budget, self.budget + self.increase)
        else:
            return self.budget  # inside the hysteresis band: hold
        if new != self.budget:
            self.budget = new
            self.adjustments += 1
        return self.budget


@dataclass
class _TenantState:
    name: str
    weight: float
    quota: Optional[float]
    max_queued: Optional[int]
    queue: list = field(default_factory=list)  # sorted on demand (small)
    vtime: float = 0.0        # WFQ virtual clock (advances by cost/weight)
    bucket: float = 0.0       # available quota tokens (can go into debt)
    last_refill: float = 0.0
    last_active: float = 0.0  # last admit/charge (idle-tenant reaping)
    tokens_used: float = 0.0  # lifetime emitted tokens (the quota gauge)

    def sort_key(self, seq_of):
        """Head-of-queue order: priority class desc, deadline asc (None
        last), then arrival order — requeued (preempted) requests carry a
        negative seq so they resume before fresh arrivals of their
        class. EDF compares ABSOLUTE deadlines (submit time + the
        relative ``deadline_s`` hint): a request submitted earlier with a
        longer hint can still expire before a late arrival with a short
        one."""
        def key(req):
            dl = getattr(req, "deadline_s", None)
            if dl is not None:
                dl += getattr(req, "submit_t", 0.0) or 0.0
            return (-int(getattr(req, "priority", 0) or 0),
                    dl if dl is not None else float("inf"),
                    seq_of(req))
        return key


class MultiTenantScheduler:
    """Weighted-fair, quota-metered, priority-classed request queue with
    admission control — the host policy tier ``ServingEngine`` consults.

    The engine owns the device work; this class only ever answers four
    questions: *may this request enter the queue* (:meth:`admit`),
    *which request goes to the freed slot next* (:meth:`next_request`),
    *which queued request should be shed under pressure*
    (:meth:`pick_shed`), and *which live slot should be paged out for a
    higher class* (:meth:`pick_victim`). All state is plain python, so
    the same object is importable on a jax-free router tier.

    Thread-safe: ``ServingEngine.serve()`` admits from other threads'
    ``submit()`` calls, so every method that touches the per-tenant
    queues holds an internal lock — an ``admit`` appending mid
    ``next_request`` sort would otherwise crash the serving loop.
    """

    def __init__(self, config: Optional[SchedulerConfig] = None, *,
                 now_fn: Callable[[], float] = time.monotonic):
        self.config = config or SchedulerConfig()
        self._now = now_fn
        self.tenants: dict = {}
        self._lock = threading.RLock()
        self._seq = itertools.count()
        self._req_seq: dict = {}      # id(req) is unstable; key by req.id
        self._requeue_seq = 0         # decreasing: resumed before fresh
        self._billed: set = set()     # requeued req ids: WFQ cost already paid
        self._vclock = 0.0            # system virtual time (last pop's vtime)
        self.admitted = 0
        self.rejected = 0
        self.shed_queued = 0

    # -- tenants -----------------------------------------------------------

    def tenant(self, name: str) -> _TenantState:
        with self._lock:
            t = self.tenants.get(name)
            if t is None:
                cfg = self.config.tenants.get(name)
                if cfg is None:
                    # unconfigured tenants get the global per-tenant bound;
                    # an EXPLICIT TenantConfig keeps its max_queued as
                    # written — None there means "global bound only" (the
                    # one way to exempt a tenant from the default)
                    cfg = TenantConfig(
                        weight=self.config.default_weight,
                        max_queued=self.config.max_tenant_queue_depth,
                    )
                self._reap_idle_tenants()
                now = self._now()
                t = self.tenants[name] = _TenantState(
                    name=name, weight=max(1e-6, float(cfg.weight)),
                    quota=cfg.quota, max_queued=cfg.max_queued,
                    last_refill=now, last_active=now,
                )
                if t.quota:
                    t.bucket = float(t.quota)  # start with a full window
            return t

    def _reap_idle_tenants(self):
        """Bound the tenant-state map: rotating tenant ids (one per user,
        say) must not grow the dict — and the per-tenant gauge family —
        without bound. Oldest-refilled idle tenants (empty queue,
        unconfigured) are dropped when a new name would exceed
        ``max_tenants``; their WFQ clock and bucket are simply rebuilt on
        the next admit, which the idle-start vtime fix makes safe."""
        limit = self.config.max_tenants
        if limit is None or len(self.tenants) < limit:
            return
        idle = sorted(
            (t for t in self.tenants.values()
             if not t.queue and t.name not in self.config.tenants),
            key=lambda t: t.last_active,
        )
        for t in idle[: max(1, len(self.tenants) - limit + 1)]:
            del self.tenants[t.name]

    def _refill(self, t: _TenantState):
        if not t.quota:
            return
        now = self._now()
        dt = max(0.0, now - t.last_refill)
        t.last_refill = now
        rate = t.quota / max(1e-9, self.config.quota_window_s)
        t.bucket = min(float(t.quota), t.bucket + rate * dt)

    # -- admission control -------------------------------------------------

    @property
    def total_queued(self) -> int:
        with self._lock:
            return sum(len(t.queue) for t in self.tenants.values())

    def admit(self, req) -> tuple:
        """Queue-depth backpressure at submit: ``(True, None)`` and the
        request is queued, or ``(False, shed_reason)`` — the caller
        records a shed, never an exception."""
        with self._lock:
            if self.total_queued >= self.config.max_queue_depth:
                self.rejected += 1
                return False, SHED_QUEUE_FULL
            t = self.tenant(getattr(req, "tenant", "default") or "default")
            if t.max_queued is not None and len(t.queue) >= t.max_queued:
                self.rejected += 1
                return False, SHED_TENANT_QUEUE_FULL
            # WFQ start-time fix: a tenant waking from idle must not replay
            # the virtual time it sat out, or it would monopolize the slots.
            # With no backlogged tenant to floor against (queues drain
            # instantly in steady state), the system virtual clock — the
            # vtime of the last scheduled tenant — is the reference
            if not t.queue:
                active = [s.vtime for s in self.tenants.values() if s.queue]
                t.vtime = max(t.vtime, min(active) if active else self._vclock)
            self._req_seq[req.id] = next(self._seq)
            t.queue.append(req)
            t.last_active = self._now()
            self.admitted += 1
            return True, None

    def requeue(self, req):
        """A preempted request re-enters at the *front* of its class
        (negative seq): it already paid its queue wait once."""
        with self._lock:
            t = self.tenant(getattr(req, "tenant", "default") or "default")
            self._requeue_seq -= 1
            self._req_seq[req.id] = self._requeue_seq
            self._billed.add(req.id)  # its WFQ cost was paid on the first pop
            t.queue.append(req)

    def remove(self, req) -> bool:
        """Drop one queued request (cancel/timeout/shed); False if it is
        not queued here."""
        with self._lock:
            t = self.tenants.get(getattr(req, "tenant", "default") or "default")
            if t is None:
                return False
            try:
                t.queue.remove(req)
            except ValueError:
                return False
            self._req_seq.pop(req.id, None)
            self._billed.discard(req.id)
            return True

    def queued(self) -> list:
        """Snapshot of every queued request (reap/timeout scans)."""
        with self._lock:
            return [r for t in self.tenants.values() for r in t.queue]

    # -- the scheduling decision ---------------------------------------------

    def _seq_of(self, req) -> int:
        return self._req_seq.get(req.id, 0)

    def _head(self, t: _TenantState):
        t.queue.sort(key=t.sort_key(self._seq_of))
        return t.queue[0]

    def _pool(self) -> list:
        """The tenants the next pop may schedule from: everyone with
        work, quota-filtered unless every queued tenant is over quota
        (work-conserving fallback). Refills buckets as a side effect."""
        candidates = [t for t in self.tenants.values() if t.queue]
        if not candidates:
            return []
        for t in candidates:
            self._refill(t)
        pool = [t for t in candidates if not t.quota or t.bucket > 0]
        return pool or candidates  # work-conserving: idle capacity is never wasted

    def peek_priority(self) -> Optional[int]:
        """Highest priority class the next pop could actually schedule
        (None when idle) — what the engine compares against live slots
        to decide preemption. Uses the same quota-filtered pool as
        :meth:`next_request`: an over-quota tenant's waiting class must
        not trigger a preemption that the pop then refuses to fill
        (equal-priority preempt/re-admit churn)."""
        with self._lock:
            pool = self._pool()
            if not pool:
                return None
            return max(
                int(getattr(self._head(t), "priority", 0) or 0) for t in pool
            )

    def next_request(self):
        """Pop the request the freed slot should run: strict priority
        class first; within the class, the in-quota tenant with the
        smallest virtual time (WFQ); over-quota tenants only when no
        in-quota tenant has work (work-conserving). Returns None when
        idle."""
        with self._lock:
            pool = self._pool()
            if not pool:
                return None
            best_prio = max(
                int(getattr(self._head(t), "priority", 0) or 0) for t in pool
            )
            pool = [
                t for t in pool
                if int(getattr(self._head(t), "priority", 0) or 0) == best_prio
            ]
            t = min(pool, key=lambda s: (s.vtime, s.name))
            # the popped tenant has the minimum vtime among backlogged
            # tenants = the system virtual time (floors idle wake-ups)
            self._vclock = max(self._vclock, t.vtime)
            req = t.queue.pop(0)
            self._req_seq.pop(req.id, None)
            # bill the WFQ cost exactly once per request: a preempted request
            # re-popped after requeue() (or a cancelled one popped and
            # discarded) must not advance its tenant's clock again — the
            # tenant a high-priority class preempts would otherwise also lose
            # its fair share, double-punished for interference it didn't cause
            if req.id in self._billed:
                self._billed.discard(req.id)
            elif not getattr(req, "done", False):
                cost = float(req.prompt.size + req.max_new_tokens)
                t.vtime += cost / t.weight
            return req

    # -- quotas --------------------------------------------------------------

    def note_tokens(self, tenant: str, n: int):
        """Charge ``n`` emitted tokens to the tenant's bucket (the engine
        calls this per token — generation, not submission, is what a
        quota meters)."""
        with self._lock:
            t = self.tenant(tenant or "default")
            t.tokens_used += n
            t.last_active = self._now()
            if t.quota:
                self._refill(t)
                # debt is floored at one window's quota: tokens generated
                # via the work-conserving fallback while everyone else was
                # idle must not starve the tenant for unbounded time once
                # contention returns — quotas bound *contended* share
                t.bucket = max(-float(t.quota), t.bucket - n)

    # -- pressure decisions --------------------------------------------------

    def pick_shed(self, max_priority: Optional[int] = None):
        """The queued request load shedding drops next: lowest priority
        class first, newest arrival within it (it has waited least, so
        dropping it wastes the least). ``max_priority`` restricts to
        classes strictly below it. Returns None when nothing qualifies.
        The caller still owns the terminal bookkeeping (this only picks)."""
        with self._lock:
            best = None
            best_key = None
            for t in self.tenants.values():
                for req in t.queue:
                    p = int(getattr(req, "priority", 0) or 0)
                    if max_priority is not None and p >= max_priority:
                        continue
                    key = (p, -self._seq_of(req))
                    if best_key is None or key < best_key:
                        best, best_key = req, key
            return best

    def shed(self, req) -> bool:
        """Remove a picked request and count the shed."""
        with self._lock:
            if self.remove(req):
                self.shed_queued += 1
                return True
            return False

    def pick_victim(self, live: Iterable, min_priority: int):
        """The live (slot, request) pair preemption should page out for
        an incoming request of ``min_priority``: the lowest class
        *strictly below* it (equal classes never preempt each other —
        that would thrash), least generated tokens within the class (the
        cheapest replay). Returns ``(slot, req)`` or None."""
        if not self.config.preemption:
            return None
        best = None
        best_key = None
        for slot, req in live:
            p = int(getattr(req, "priority", 0) or 0)
            if p >= min_priority:
                continue
            key = (p, len(req.tokens), -slot)
            if best_key is None or key < best_key:
                best, best_key = (slot, req), key
        return best

    # -- gauges --------------------------------------------------------------

    def metrics(self) -> dict:
        """Flat ``serving/``-namespaced gauges: global queue state plus
        one ``quota_<tenant>_*`` family per tenant (the tenant set — and
        therefore the gauge cardinality — is bounded by ``max_tenants``
        idle-reaping)."""
        with self._lock:
            out = {
                "serving/sched_queued": self.total_queued,
                "serving/sched_admitted": self.admitted,
                "serving/sched_rejected": self.rejected,
            }
            for t in self.tenants.values():
                out[f"serving/quota_{t.name}_tokens_used"] = t.tokens_used
                out[f"serving/tenant_{t.name}_queued"] = len(t.queue)
                if t.quota:
                    self._refill(t)
                    out[f"serving/quota_{t.name}_remaining_frac"] = round(
                        max(0.0, t.bucket) / t.quota, 4
                    )
            return out
