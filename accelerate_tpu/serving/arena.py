"""Slot-arena plumbing for the continuous-batching decode engine.

The arena IS the model's flax "cache" collection, created at batch =
``num_slots``: K/V leaves are ``[..., num_slots, KVH, max_cache_len, D]``
(a leading layer axis under ``scan_layers``). Each batch row is one
*slot* — an independent request at its own cache depth. Nothing here ever
changes a shape: admission writes a slot's prefix, eviction is a host-side
bookkeeping change, decode scatters one token per slot — so a live engine
triggers **zero recompiles** across admissions/evictions at any mix of
prompt lengths (asserted via the jax.monitoring compile counters,
``utils/compile_cache.compile_event_counters``).

Slot lifecycle note: a freed slot is reused WITHOUT clearing — the decode
attention path (``ops/attention.decode_attention``) masks every position
past a slot's frontier, and both prefill chunks and decode steps write a
position before it can be attended, so a previous occupant's stale K/V is
unreachable by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# K/V cache leaves are [B, KVH, L, D] (+ an optional leading layer axis
# from nn.scan); anything of lower rank is a cache_index bookkeeping leaf
_KV_NDIM = 4


def _is_kv(leaf) -> bool:
    return getattr(leaf, "ndim", 0) >= _KV_NDIM


def _slot_axis(leaf) -> int:
    return leaf.ndim - _KV_NDIM


def init_arena(definition, params, num_slots: int, placer):
    """All-zeros cache arena shaped for ``num_slots`` concurrent requests.
    Shapes come from ``jax.eval_shape`` over the batched decode apply — no
    compile, no device compute, and automatically correct for any cache
    layout the model family uses (scan vs. unrolled layers, GQA, dtypes)."""

    def shape_fn(p):
        _, mutated = definition.apply(
            {"params": placer(p)},
            jnp.zeros((num_slots, 1), jnp.int32),
            positions=jnp.zeros((num_slots, 1), jnp.int32),
            use_cache=True,
            decode=True,
            cache_positions=jnp.zeros((num_slots,), jnp.int32),
            mutable=["cache"],
        )
        return mutated["cache"]

    shapes = jax.eval_shape(shape_fn, params)
    return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def arena_num_slots(arena) -> int:
    for leaf in jax.tree_util.tree_leaves(arena):
        if _is_kv(leaf):
            return int(leaf.shape[_slot_axis(leaf)])
    raise ValueError("arena holds no K/V leaves")


def arena_nbytes(arena) -> int:
    return sum(int(l.nbytes) for l in jax.tree_util.tree_leaves(arena))


def slot_view(arena, slot, start):
    """Batch-1 cache tree for one slot (dynamic slice along the slot axis).
    ``cache_index`` leaves become ``start``, so the scalar-index decode
    path (the one chunked prefill rides) continues this slot exactly where
    its previous chunk stopped. Traced-friendly: ``slot``/``start`` may be
    tracers, keeping the caller's jit free of per-slot recompiles."""

    def take(leaf):
        if _is_kv(leaf):
            return jax.lax.dynamic_slice_in_dim(leaf, slot, 1, axis=_slot_axis(leaf))
        return jnp.full(leaf.shape, start, leaf.dtype)

    return jax.tree_util.tree_map(take, arena)


def write_slot(arena, slot_tree, slot):
    """Write a batch-1 slot tree's K/V back into the arena. Index leaves
    keep the arena's value — per-slot progress lives in the engine's
    ``lengths`` vector, not in the collection."""

    def put(a, s):
        if _is_kv(a):
            return jax.lax.dynamic_update_slice_in_dim(
                a, s.astype(a.dtype), slot, axis=_slot_axis(a)
            )
        return a

    return jax.tree_util.tree_map(put, arena, slot_tree)
