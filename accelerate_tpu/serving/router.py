"""Multi-replica serving router: placement, failover, re-queue.

One :class:`~.engine.ServingEngine` process serves one host's devices; a
production deployment is N replica processes behind a front door. This
module is that front door, and its headline property is **robustness**:
kill any replica mid-burst and every request still reaches a definite,
token-exact outcome. Plain stdlib — no jax/flax/numpy (declared in
``analysis/hygiene.py``'s jax-free set): the router runs on a box with
no accelerator stack.

- **placement** — least-loaded off the PR 11 signal contract: a
  :class:`~..telemetry.fleet.FleetCollector` polls every replica's
  ``/metrics`` scrape and ``placement_view()`` ranks them by
  ``serving/load_score``; **session affinity** pins a ``session`` id to
  the replica that served it last (its prefix-cache pages make repeat
  TTFT near-zero), falling back to least-loaded — and migrating the
  session's KV through the handoff endpoints — when that replica drains
  or dies.
- **failover + re-queue** — a connection refusal, a read timeout, or a
  stream that ends without a terminal event marks the replica failed
  (excluded immediately, before the health machine's next poll
  confirms) and re-queues the request onto a surviving replica with the
  same ``request_id``, so the per-replica request logs stitch into one
  hop-by-hop timeline (``accelerate-tpu trace summary --request-id``).
  Tokens already streamed are never re-emitted: the replay is
  token-exact by engine determinism (same seed, same prompt), and the
  router skips the prefix it already delivered.
- **backoff** — capped exponential with deterministic seeded jitter
  (:func:`backoff_schedule`): the schedule is a pure function of
  ``(backoff_seed, request_id)``, so a failing drill replays the exact
  same waits.
- **bounded queues** — admission past ``max_inflight`` sheds with
  ``shed_reason="router_queue_full"`` (a value, not an exception, same
  as the engine's admission control); no-replica and retries-exhausted
  paths shed too. The router never stalls a caller indefinitely.
- **golden signals** — client-observed streaming histograms (TTFT, ITL,
  e2e, queue-wait, placement wall, backoff wait) on the shared
  log-bucket layout (``telemetry/histograms.py``), rendered natively on
  ``/metrics`` so a fleet collector exact-merges them; per-hop timing
  stamps (``place_start``/``connect``/``first_token`` on the router's
  one clock) feeding the latency waterfall (``telemetry/waterfall.py``);
  and a bounded **placement-decision log** (``router-decisions.jsonl``:
  request, candidate scores, chosen replica, affinity reason) answering
  "why was it placed THERE". ``RouterConfig(instrument=False)`` is the
  zero-overhead baseline the tier-1 witness compares against.
- **elastic membership** — replicas register/deregister at runtime
  (HTTP ``/v1/register`` // ``/v1/deregister`` or
  :meth:`Router.register_replica`); a draining replica takes no new
  placements but stays visible (``placement_view(include_draining=
  True)``) so its in-flight streams finish and its cached KV can be
  exported.

Fault injection: the PR 7 :class:`~.faults.FaultInjector` gained
network-level faults (connection-refused, slow-replica, mid-stream
drop); pass one as ``Router(..., faults=...)`` and the transport layer
consults it — the same seeded injector drives single-engine scheduler
drills and multi-replica kill drills.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..telemetry.fleet import DOWN_STATES, FleetCollector
from ..telemetry.histograms import StreamingHistogram, percentile_keys
from .faults import StreamDropped

# terminal router shed reasons (same bounded-vocabulary contract as the
# engine scheduler's SHED_* constants — dashboards group on these)
SHED_ROUTER_QUEUE_FULL = "router_queue_full"  # max_inflight at submit
SHED_NO_REPLICAS = "no_replicas"              # nothing placeable, ever
SHED_RETRIES_EXHAUSTED = "retries_exhausted"  # every hop failed


def backoff_schedule(seed, request_id, attempts: int, *,
                     base_s: float = 0.05, cap_s: float = 2.0) -> list:
    """The re-queue backoff schedule: capped exponential with
    deterministic seeded jitter. A pure function of
    ``(seed, request_id)`` — the same request under the same router
    config always waits the same intervals, so a failing burst drill is
    a repro, not an anecdote. Jitter spans [0.5x, 1x] of the capped
    exponential term (never zero: a thundering re-queue herd after a
    replica death must decorrelate)."""
    rng = random.Random(f"{seed}/{request_id}")
    out = []
    for i in range(attempts):
        base = min(float(cap_s), float(base_s) * (2.0 ** i))
        out.append(base * (0.5 + 0.5 * rng.random()))
    return out


@dataclass
class RouterConfig:
    """Knobs for :class:`Router` (docs/serving.md has the tuning
    guide)."""

    max_inflight: int = 64            # bounded router queue; past it -> shed
    max_retries: int = 4              # re-queue attempts after the first hop
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    backoff_seed: int = 0
    request_timeout_s: Optional[float] = None  # wall from submit to cancel
    connect_timeout_s: float = 5.0
    read_timeout_s: float = 60.0      # per-read; a silent replica is a failure
    poll_interval_s: float = 0.25     # health/placement scrape cadence
    failure_cooldown_s: float = 10.0  # in-flight failure excludes this long
    affinity: bool = True             # session -> last-replica stickiness
    migrate_session_kv: bool = True   # KV handoff when a session moves
    # -- golden signals (docs/telemetry.md "Router golden signals") --------
    instrument: bool = True           # hop stamps + histograms + decisions
    log_dir: Optional[str] = None     # router-requests.jsonl / router-decisions.jsonl
    decision_log_max: int = 256       # bounded in-memory decision ring
    decision_candidates_max: int = 8  # candidate-score rows kept per decision


@dataclass(eq=False)
class RouterRequest:
    """One logical request and its hop history (``eq=False`` for the
    same identity-not-value reason as the engine's ``Request``). The
    ``request_id`` is stable across hops — every replica's request log
    carries it, which is what makes the re-queue path observable end to
    end."""

    id: object
    prompt: list
    max_new_tokens: int
    seed: int
    session: Optional[str] = None
    tenant: str = "default"
    priority: int = 0

    tokens: list = field(default_factory=list)
    hops: list = field(default_factory=list)   # {replica, t_unix_s, error?}
    replica: Optional[str] = None              # who finished it
    outcome: Optional[str] = None              # finished | shed | cancelled
    finish_reason: Optional[str] = None
    shed_reason: Optional[str] = None
    requeues: int = 0
    prefix_hit: int = 0
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    finish_t: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.outcome is not None


class HttpTransport:
    """The stdlib replica transport: JSONL streaming submit plus plain
    JSON POSTs (cancel, KV export/import). Injectable — the jax-free
    router unit tests script a fake; the drills run this one."""

    def __init__(self, *, connect_timeout_s: float = 5.0,
                 read_timeout_s: float = 60.0):
        self.connect_timeout_s = float(connect_timeout_s)
        self.read_timeout_s = float(read_timeout_s)

    def _conn(self, base_url: str):
        import http.client
        from urllib.parse import urlsplit

        parts = urlsplit(base_url)
        if parts.scheme not in ("http", ""):
            raise ValueError(f"replica transport is http-only, got {base_url!r}")
        host = parts.hostname or parts.path.split("/")[0]
        return http.client.HTTPConnection(
            host, parts.port or 80, timeout=self.connect_timeout_s
        )

    def stream_submit(self, base_url: str, payload: dict, *,
                      on_event: Callable[[dict], None]) -> dict:
        """POST ``/v1/submit`` and feed each JSONL event to
        ``on_event``; returns the terminal ``done`` event. EOF before a
        terminal event raises :class:`StreamDropped` — the caller's
        re-queue trigger."""
        conn = self._conn(base_url)
        try:
            body = json.dumps(payload).encode()
            conn.request("POST", "/v1/submit", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                raise ConnectionError(
                    f"replica {base_url} answered {resp.status} to submit"
                )
            if conn.sock is not None:
                # a replica that stops emitting (wedged, paused mid-kill)
                # is a failure, not a hang: bound every read
                conn.sock.settimeout(self.read_timeout_s)
            while True:
                line = resp.readline()
                if not line:
                    raise StreamDropped(
                        f"stream from {base_url} ended without a terminal event"
                    )
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    # a torn final line IS the mid-write death signature
                    raise StreamDropped(
                        f"torn stream line from {base_url}"
                    ) from None
                on_event(event)
                if event.get("event") == "done":
                    return event
        finally:
            conn.close()

    def post_json(self, base_url: str, path: str, payload: dict) -> dict:
        conn = self._conn(base_url)
        try:
            conn.request("POST", path, body=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise ConnectionError(
                    f"replica {base_url}{path} answered {resp.status}: "
                    f"{data[:200]!r}"
                )
            return json.loads(data) if data else {}
        finally:
            conn.close()

    def get_json(self, base_url: str, path: str) -> dict:
        conn = self._conn(base_url)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise ConnectionError(
                    f"replica {base_url}{path} answered {resp.status}: "
                    f"{data[:200]!r}"
                )
            return json.loads(data) if data else {}
        finally:
            conn.close()


class Router:
    """Least-loaded + session-affinity placement with failover/re-queue
    over N replica servers. ``replicas`` is ``{name: base_url}`` (or
    ``(name, url)`` pairs); more join/leave at runtime via
    :meth:`register_replica` / :meth:`deregister_replica`.

    ``submit()`` is synchronous (the HTTP front door runs it on its
    handler threads; drills run it on their own): it places, streams,
    and — on a replica failure — re-queues with the failed replica
    excluded, until the request reaches exactly one terminal outcome.
    """

    def __init__(self, replicas=None, *, config: Optional[RouterConfig] = None,
                 transport=None, faults=None, fetch_fn=None,
                 clock: Callable[[], float] = time.time,
                 collector: Optional[FleetCollector] = None):
        self.config = config or RouterConfig()
        self._clock = clock
        pairs = []
        if replicas:
            items = replicas.items() if isinstance(replicas, dict) else replicas
            pairs = [(str(n), str(u).rstrip("/")) for n, u in items]
        self._lock = threading.Lock()
        self._replicas = dict(pairs)           # name -> base_url
        self._sessions: dict = {}              # session -> replica name
        self._failed: dict = {}                # name -> last in-flight failure t
        self._inflight = 0
        self._next_id = 0
        self.transport = transport or HttpTransport(
            connect_timeout_s=self.config.connect_timeout_s,
            read_timeout_s=self.config.read_timeout_s,
        )
        self._faults = faults
        self.collector = collector or FleetCollector(
            [(n, self._metrics_target(u)) for n, u in pairs],
            poll_interval_s=self.config.poll_interval_s,
            fetch_fn=fetch_fn, clock=clock,
        )
        # counters (the router's own gauge contract, /metrics-rendered)
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_shed = 0
        self.requests_cancelled = 0
        self.requeues = 0           # failed HOPS (a request can add >1)
        self.requests_requeued = 0  # REQUESTS that survived >=1 failed hop
        self.requeue_success = 0    # ...and still finished
        self.kv_migrations = 0
        self.replica_failures: dict = {}       # name -> count
        self.shed_reason_counts: dict = {}     # reason -> count
        # golden signals: client-observed streaming histograms (the same
        # log-bucket layout every session uses, so the fleet collector
        # exact-merges the native /metrics buckets across routers) + the
        # bounded placement-decision ring. config.instrument=False is the
        # zero-overhead witness baseline the tier-1 drill compares against.
        self.instrument = bool(self.config.instrument)
        self.hists: dict = {}
        if self.instrument:
            for key in ("router/ttft", "router/itl", "router/e2e",
                        "router/queue_wait", "router/placement",
                        "router/backoff_wait"):
                self.hists[key] = StreamingHistogram()
        self.decisions: list = []   # bounded ring of placement decisions
        self.canary = None          # optional attached CanaryProber
        self.autoscaler = None      # optional attached Autoscaler
        self._log_lock = threading.Lock()
        self._decisions_fh = None
        self._requests_fh = None
        if self.config.log_dir and self.instrument:
            from ..telemetry.artifacts import ArtifactWriter

            self._decisions_fh = ArtifactWriter(
                os.path.join(self.config.log_dir, "router-decisions.jsonl")
            )
            self._requests_fh = ArtifactWriter(
                os.path.join(self.config.log_dir, "router-requests.jsonl")
            )

    @staticmethod
    def _metrics_target(base_url: str) -> str:
        return base_url.rstrip("/") + "/metrics"

    # -- membership ---------------------------------------------------------

    def register_replica(self, name: str, base_url: str) -> None:
        """Elastic join: the replica enters placement as soon as its
        first scrape lands (state machine: starting -> healthy)."""
        name, base_url = str(name), str(base_url).rstrip("/")
        with self._lock:
            self._replicas[name] = base_url
            self._failed.pop(name, None)
        self.collector.add_replica(name, self._metrics_target(base_url))

    def deregister_replica(self, name: str) -> bool:
        """Elastic leave: gone from placement immediately. In-flight
        streams on the replica are unaffected (their connections stand);
        sticky sessions fall back to least-loaded on their next
        request."""
        name = str(name)
        with self._lock:
            known = self._replicas.pop(name, None) is not None
            self._failed.pop(name, None)
            for session, replica in list(self._sessions.items()):
                if replica == name:
                    del self._sessions[session]
        self.collector.remove_replica(name)
        return known

    def start(self) -> "Router":
        """Run the health/placement poll on its background cadence."""
        self.collector.start()
        return self

    def close(self):
        if self.autoscaler is not None:
            try:
                self.autoscaler.close()
            except Exception:
                pass
        if self.canary is not None:
            try:
                self.canary.close()
            except Exception:
                pass
        self.collector.close()
        with self._log_lock:
            for fh in (self._decisions_fh, self._requests_fh):
                if fh is not None:
                    try:
                        fh.close()
                    except OSError:
                        pass
            self._decisions_fh = self._requests_fh = None

    # -- golden signals ------------------------------------------------------

    def _observe(self, key: str, seconds: float, exemplar=None):
        h = self.hists.get(key)
        if h is not None:
            h.observe(seconds, exemplar=exemplar)

    @staticmethod
    def _exemplar(req: RouterRequest, replica=None) -> dict:
        ex = {"request_id": req.id}
        replica = replica or getattr(req, "replica", None)
        if replica:
            ex["replica"] = str(replica)
        return ex

    def _note_decision(self, req: RouterRequest, hop_index: int,
                       chosen: str, rows: list, excluded, reason: str,
                       now: float):
        """One placement decision: who won, why, and the candidate-score
        snapshot it won against — the 'why was it placed THERE' record a
        latency regression triage starts from."""
        entry = {
            "t_unix_s": round(now, 3),
            "request_id": req.id,
            "hop": int(hop_index),
            "session": req.session,
            "chosen": chosen,
            "reason": reason,
            "excluded": [str(e) for e in excluded],
            "candidates": [
                {"replica": r.get("replica"),
                 "load_score": r.get("load_score"),
                 "state": r.get("state"),
                 "placeable": bool(r.get("placeable", True))}
                for r in rows[: self.config.decision_candidates_max]
            ],
        }
        with self._log_lock:
            self.decisions.append(entry)
            cap = max(1, int(self.config.decision_log_max))
            if len(self.decisions) > cap:
                del self.decisions[: len(self.decisions) - cap]
            fh = self._decisions_fh
            if fh is not None:
                fh.write_line(json.dumps(entry))

    def _finalize(self, req: RouterRequest):
        """Terminal bookkeeping for every outcome path: the e2e
        histogram and the router request record (the waterfall's
        router-side half)."""
        if not self.instrument:
            return
        if req.finish_t is not None:
            self._observe("router/e2e", max(0.0, req.finish_t - req.submit_t),
                          exemplar=self._exemplar(req))
        fh = self._requests_fh
        if fh is None:
            return
        rec = {
            "request_id": req.id,
            "session": req.session,
            "tenant": req.tenant,
            "submit_unix_s": round(req.submit_t, 6),
            "outcome": req.outcome,
            "finish_reason": req.finish_reason,
            "shed_reason": req.shed_reason,
            "replica": req.replica,
            "tokens": len(req.tokens),
            "requeues": sum(1 for h in req.hops if "error" in h),
            "ttft_ms": (
                round((req.first_token_t - req.submit_t) * 1e3, 3)
                if req.first_token_t is not None else None
            ),
            "e2e_ms": (
                round((req.finish_t - req.submit_t) * 1e3, 3)
                if req.finish_t is not None else None
            ),
            "hops": req.hops,
        }
        with self._log_lock:
            if self._requests_fh is not None:
                self._requests_fh.write_line(json.dumps(rec))

    # -- placement ----------------------------------------------------------

    def _failed_now(self, now: float) -> set:
        with self._lock:
            return {
                n for n, t in self._failed.items()
                if now - t < self.config.failure_cooldown_s
            }

    def _note_failure(self, name: str, now: float):
        with self._lock:
            self._failed[name] = now
            self.replica_failures[name] = self.replica_failures.get(name, 0) + 1

    def candidates(self, session: Optional[str] = None, exclude=()) -> list:
        """Placement order for one hop: the collector's score-ranked
        placeable view, minus excluded/recently-failed replicas, with
        the session's sticky replica promoted to the front when it is
        still placeable. Returns replica names."""
        return self._ranked(session, exclude)[0]

    def _ranked(self, session: Optional[str], exclude=()) -> tuple:
        """``(names, rows, sticky)`` — the ranked placement order plus
        the score rows it was ranked from (the decision log snapshots
        them) and the session's sticky replica (None when absent)."""
        now = self._clock()
        rows = self.collector.placement_view()
        failed = self._failed_now(now)
        with self._lock:
            known = set(self._replicas)
            sticky = self._sessions.get(session) if session else None
        names = [
            r["replica"] for r in rows
            if r["replica"] in known
            and r["replica"] not in exclude
            and r["replica"] not in failed
        ]
        if self.config.affinity and sticky in names:
            names.remove(sticky)
            names.insert(0, sticky)
        return names, rows, sticky

    def _replica_url(self, name: str) -> Optional[str]:
        with self._lock:
            return self._replicas.get(name)

    def _sticky_source(self, session: Optional[str], target: str):
        """(name, url) of the session's previous replica when the
        session is migrating off it and its KV may still be exportable
        (reachable or draining — NOT dead), else None."""
        if not session or not self.config.migrate_session_kv:
            return None
        with self._lock:
            sticky = self._sessions.get(session)
            url = self._replicas.get(sticky) if sticky else None
        if sticky is None or sticky == target or url is None:
            return None
        for row in self.collector.placement_view(include_unplaceable=True):
            if row["replica"] != sticky:
                continue
            if row["state"] in DOWN_STATES:
                return None
            return sticky, url
        return None

    def _migrate_session_kv(self, req: RouterRequest, target: str,
                            target_url: str):
        """Best-effort KV handoff when a sticky session moves: export
        the prompt's cached pages from the old replica, import into the
        new one, so the migrated session's next admission is still a
        prefix hit. Failure is absorbed — the request just pays a cold
        prefill."""
        src = self._sticky_source(req.session, target)
        if src is None:
            return
        src_name, src_url = src
        try:
            handoff = self.transport.post_json(
                src_url, "/v1/kv/export", {"tokens": list(req.prompt)}
            )
            if handoff and handoff.get("n_pages"):
                out = self.transport.post_json(
                    target_url, "/v1/kv/import", handoff
                )
                if out.get("installed_tokens"):
                    with self._lock:
                        self.kv_migrations += 1
                    req.hops.append({
                        "replica": target, "t_unix_s": round(self._clock(), 3),
                        "kv_migrated_from": src_name,
                        "kv_tokens": int(out["installed_tokens"]),
                    })
        except (OSError, ConnectionError, ValueError):
            pass

    def kv_directory(self) -> dict:
        """Merged prefix directory across every reachable replica — the
        fleet's advertised warm-KV inventory (the peer tier's discovery
        contract, ``docs/serving.md``). Each digest maps to its longest
        advertised prefix and the replicas holding it; an unreachable
        replica is simply absent (a directory is a hint, never truth —
        the pull itself re-validates)."""
        with self._lock:
            replicas = dict(self._replicas)
        merged: dict = {}
        for name, url in replicas.items():
            try:
                doc = self.transport.get_json(url, "/v1/kv/directory")
            except (OSError, ConnectionError, ValueError):
                continue
            for row in (doc or {}).get("prefixes") or []:
                if not isinstance(row, dict) or not row.get("digest"):
                    continue
                d = str(row["digest"])
                cur = merged.setdefault(
                    d, {"digest": d, "token_len": 0, "replicas": []}
                )
                cur["token_len"] = max(
                    cur["token_len"], int(row.get("token_len") or 0)
                )
                cur["replicas"].append(name)
        return {"version": 1, "prefixes": sorted(
            merged.values(), key=lambda r: r["digest"]
        )}

    # -- the request path ---------------------------------------------------

    def submit(self, prompt, *, max_new_tokens: int = 32, seed: int = 0,
               session: Optional[str] = None, tenant: str = "default",
               priority: int = 0, request_id=None,
               timeout_s: Optional[float] = None,
               on_token: Optional[Callable] = None) -> RouterRequest:
        """Route one request to completion. Returns the terminal
        :class:`RouterRequest` — outcome ``finished``, ``shed`` (with
        ``shed_reason``), or ``cancelled`` (timeout); never raises for a
        replica-side failure and never hangs (bounded retries, bounded
        waits). ``on_token(token, req)`` fires once per emitted token
        across all hops — a re-queued replay's already-delivered prefix
        is skipped, not re-emitted."""
        with self._lock:
            self.requests_submitted += 1
            if request_id is None:
                request_id = f"r{self._next_id}"
                self._next_id += 1
            admitted = self._inflight < max(0, int(self.config.max_inflight))
            if admitted:
                self._inflight += 1
        req = RouterRequest(
            id=request_id, prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens), seed=int(seed),
            session=session, tenant=str(tenant or "default"),
            priority=int(priority),
        )
        req.submit_t = self._clock()
        if not admitted:
            self._shed(req, SHED_ROUTER_QUEUE_FULL)
            return req
        try:
            self._route(req, timeout_s, on_token)
        finally:
            with self._lock:
                self._inflight -= 1
        return req

    def _shed(self, req: RouterRequest, reason: str):
        req.outcome = "shed"
        req.finish_reason = "shed"
        req.shed_reason = reason
        req.finish_t = self._clock()
        with self._lock:
            self.requests_shed += 1
            self.shed_reason_counts[reason] = (
                self.shed_reason_counts.get(reason, 0) + 1
            )
            if any("error" in h for h in req.hops):
                self.requests_requeued += 1
        self._finalize(req)

    def _deadline(self, req: RouterRequest, timeout_s) -> Optional[float]:
        timeout_s = timeout_s if timeout_s is not None \
            else self.config.request_timeout_s
        return req.submit_t + timeout_s if timeout_s is not None else None

    def _backoff_sleep(self, seconds: float) -> float:
        """One backoff wait, measured (the waterfall's retry_backoff
        stage and the ``router/backoff_wait`` histogram both come from
        the measured wall, not the nominal schedule)."""
        t0 = self._clock()
        time.sleep(seconds)
        waited = max(0.0, self._clock() - t0)
        self._observe("router/backoff_wait", waited)
        return waited

    def _route(self, req: RouterRequest, timeout_s, on_token):
        cfg = self.config
        delays = backoff_schedule(
            cfg.backoff_seed, req.id, cfg.max_retries + 1,
            base_s=cfg.backoff_base_s, cap_s=cfg.backoff_cap_s,
        )
        deadline = self._deadline(req, timeout_s)
        excluded: list = []
        failures = 0
        queued = False        # router/queue_wait observed yet?
        backoff_pending = 0.0  # waits since the last hop (stamped on the next)
        while True:
            now = self._clock()
            if deadline is not None and now >= deadline:
                req.outcome = "cancelled"
                req.finish_reason = "timeout"
                req.finish_t = now
                with self._lock:
                    self.requests_cancelled += 1
                    if any("error" in h for h in req.hops):
                        self.requests_requeued += 1
                self._finalize(req)
                return
            place_start = now
            if not queued:
                queued = True
                self._observe("router/queue_wait",
                              max(0.0, place_start - req.submit_t),
                              exemplar=self._exemplar(req))
            names, rows, sticky = self._ranked(req.session, exclude=excluded)
            place_end = self._clock()
            self._observe("router/placement", max(0.0, place_end - place_start),
                          exemplar=self._exemplar(req))
            if not names:
                with self._lock:
                    any_known = bool(self._replicas)
                if not any_known or failures > cfg.max_retries:
                    # keyed on the hop history, not the (clearable)
                    # exclusion list: a request whose hops failed is
                    # retries_exhausted even after an exclusion reset
                    self._shed(
                        req,
                        SHED_RETRIES_EXHAUSTED
                        if any("error" in h for h in req.hops)
                        else SHED_NO_REPLICAS,
                    )
                    return
                # replicas exist but none is placeable right now (all
                # excluded / scrapes pending): back off, refresh health,
                # then drop the per-request exclusions — the fleet view
                # has caught up, so a genuinely-bad replica stays out
                # via its health state / failure cooldown while a
                # recovered one becomes retryable again
                backoff_pending += self._backoff_sleep(
                    delays[min(failures, len(delays) - 1)]
                )
                failures += 1
                self.collector.poll_once()
                del excluded[:]
                continue
            target = names[0]
            url = self._replica_url(target)
            if url is None:
                excluded.append(target)
                continue
            if self.instrument:
                self._note_decision(
                    req, len(req.hops), target, rows, excluded,
                    "affinity" if (cfg.affinity and target == sticky)
                    else "least_loaded",
                    place_end,
                )
            if req.prompt and not req.tokens:
                self._migrate_session_kv(req, target, url)
            hop = {"replica": target, "t_unix_s": round(self._clock(), 3)}
            if self.instrument:
                # the waterfall's router-side stamps: one clock, so the
                # stage math is pure timestamp differences (waterfall.py)
                hop["place_start_unix_s"] = round(place_start, 6)
                hop["placement_ms"] = round((place_end - place_start) * 1e3, 3)
                if backoff_pending:
                    hop["backoff_before_ms"] = round(backoff_pending * 1e3, 3)
                backoff_pending = 0.0
            req.hops.append(hop)
            try:
                if self._faults is not None:
                    self._faults.before_connect(target)
                if self.instrument:
                    hop["connect_unix_s"] = round(self._clock(), 6)
                done = self.transport.stream_submit(
                    url, self._hop_payload(req, deadline),
                    on_event=lambda evt: self._on_event(
                        req, target, hop, evt, on_token
                    ),
                )
            except (OSError, ConnectionError, StreamDropped) as e:
                hop["error"] = f"{type(e).__name__}: {e}"
                self._note_failure(target, self._clock())
                excluded.append(target)
                failures += 1
                with self._lock:
                    self.requeues += 1
                if failures > cfg.max_retries:
                    self._shed(req, SHED_RETRIES_EXHAUSTED)
                    return
                backoff_pending += self._backoff_sleep(
                    delays[min(failures - 1, len(delays) - 1)]
                )
                continue
            # terminal event from the replica
            if self.instrument:
                hop["done_unix_s"] = round(self._clock(), 6)
            outcome = str(done.get("outcome") or "finished")
            if outcome == "shed" and done.get("shed_reason") == "draining":
                # the replica started draining between the scrape and our
                # connect: not a failure, just not placeable — try the
                # next one without burning a failure budget slot
                hop["error"] = "shed: draining"
                excluded.append(target)
                continue
            req.replica = target
            req.outcome = outcome
            req.finish_reason = done.get("finish_reason")
            req.shed_reason = done.get("shed_reason")
            req.prefix_hit = int(done.get("prefix_hit") or 0)
            req.finish_t = self._clock()
            with self._lock:
                crossed_failure = any("error" in h for h in req.hops[:-1])
                if crossed_failure:
                    self.requests_requeued += 1
                if outcome == "finished":
                    self.requests_completed += 1
                    if crossed_failure:
                        # survived >=1 failed hop AND finished: the
                        # numerator of router_requeue_success_rate
                        self.requeue_success += 1
                elif outcome == "shed":
                    self.requests_shed += 1
                    self.shed_reason_counts[str(req.shed_reason)] = (
                        self.shed_reason_counts.get(str(req.shed_reason), 0) + 1
                    )
                else:
                    self.requests_cancelled += 1
                if req.session and outcome == "finished":
                    self._sessions[req.session] = target
            self._finalize(req)
            return

    def _hop_payload(self, req: RouterRequest,
                     deadline: Optional[float]) -> dict:
        payload = {
            "prompt": req.prompt,
            "max_new_tokens": req.max_new_tokens,
            "seed": req.seed,
            "tenant": req.tenant,
            "priority": req.priority,
            "request_id": req.id,
            "stream": True,
        }
        if deadline is not None:
            # enforce the wall INSIDE the hop too: the replica's own
            # timeout path cancels mid-stream (terminal event outcome
            # "cancelled"), so a healthy-but-slow stream cannot outlive
            # the caller's budget between the router's loop-top checks
            payload["timeout_s"] = max(0.05, deadline - self._clock())
        return payload

    def _on_event(self, req: RouterRequest, replica: str, hop: dict,
                  event: dict, on_token):
        if self._faults is not None and event.get("event") == "token":
            self._faults.on_stream_event(replica, int(event.get("i", 0)))
        now = self._clock()
        if self.instrument and "first_byte_unix_s" not in hop:
            hop["first_byte_unix_s"] = round(now, 6)
        if event.get("event") != "token":
            return
        i = int(event["i"])
        if i < len(req.tokens):
            return  # replayed prefix after a re-queue: already delivered
        token = int(event["token"])
        req.tokens.append(token)
        if req.first_token_t is None:
            # client-observed TTFT: submit at the router to first NEW
            # token back at the router — the number the user felt
            req.first_token_t = now
            if self.instrument:
                hop["first_token_unix_s"] = round(now, 6)
                self._observe("router/ttft", max(0.0, now - req.submit_t),
                              exemplar=self._exemplar(req, hop.get("replica")))
        elif req.last_token_t is not None:
            self._observe("router/itl", max(0.0, now - req.last_token_t),
                          exemplar=self._exemplar(req, hop.get("replica")))
        req.last_token_t = now
        if on_token is not None:
            on_token(token, req)

    # -- introspection ------------------------------------------------------

    def placement(self, include_draining: bool = True) -> list:
        """The ranked placement snapshot the router is acting on (see
        ``FleetCollector.placement_view``; draining replicas included by
        default — they still serve their in-flight streams)."""
        return self.collector.placement_view(include_draining=include_draining)

    def metrics(self) -> dict:
        with self._lock:
            out = {
                "router/replicas": len(self._replicas),
                "router/inflight": self._inflight,
                "router/requests_submitted": self.requests_submitted,
                "router/requests_completed": self.requests_completed,
                "router/requests_shed": self.requests_shed,
                "router/requests_cancelled": self.requests_cancelled,
                "router/requeues": self.requeues,
                "router/requests_requeued": self.requests_requeued,
                "router/requeue_success": self.requeue_success,
                "router/kv_migrations": self.kv_migrations,
                "router/sessions": len(self._sessions),
            }
            for name, n in sorted(self.replica_failures.items()):
                out[f"router/failures/{name}"] = n
            for reason, n in sorted(self.shed_reason_counts.items()):
                out[f"router/shed/{reason}"] = n
        # golden-signal percentiles ride the rollup the same way the
        # engine's serving/* histograms do (the native buckets are also
        # exposed on /metrics, so the fleet collector exact-merges them)
        for name, hist in self.hists.items():
            out.update(percentile_keys(name, hist))
        if self.canary is not None:
            try:
                out.update(self.canary.rollup_keys())
            except Exception:
                pass  # a sick prober must not fail the scrape
        if self.autoscaler is not None:
            try:
                out.update(self.autoscaler.rollup_keys())
            except Exception:
                pass  # same contract as the prober
        return out

    def attach_canary(self, prober) -> "Router":
        """Publish an attached :class:`~..telemetry.canary.CanaryProber`'s
        ``canary/*`` gauges through this router's ``/metrics`` (the
        prober's lifecycle joins ``close()``)."""
        self.canary = prober
        return self

    def attach_autoscaler(self, autoscaler) -> "Router":
        """Publish an attached :class:`~.autoscaler.Autoscaler`'s
        ``autoscale/*`` gauges through this router's ``/metrics`` (its
        lifecycle joins ``close()``)."""
        self.autoscaler = autoscaler
        return self


class _RouterMetricsSession:
    """`prometheus_text` shim over the router's counters (the same
    pattern as the replica server's engine-gauges shim)."""

    def __init__(self, router: Router):
        self.router = router
        # the golden-signal histograms render natively (_bucket{le=...})
        # so a FleetCollector scraping N routers exact-merges quantiles
        self.hists = router.hists
        self.alerts = None
        self.last_sample_unix_s = None  # counters are live, not sampled

    def rollup(self) -> dict:
        return self.router.metrics()


class RouterServer:
    """The stdlib-HTTP/JSONL front door over a :class:`Router`:

    - ``POST /v1/submit`` — body ``{prompt, max_new_tokens, seed,
      session?, tenant?, priority?, request_id?, timeout_s?}``; streams
      ``{"event": "token", ...}`` JSONL lines and one terminal
      ``{"event": "done", ...}`` (failover happens underneath — the
      client sees one uninterrupted, token-exact stream);
    - ``POST /v1/register`` / ``POST /v1/deregister`` — elastic replica
      membership (``{name, url}`` / ``{name}``);
    - ``GET /v1/placement`` — the ranked placement snapshot (JSON);
    - ``GET /metrics`` — the router's own counters as Prometheus text.
    """

    def __init__(self, router: Router, *, host: str = "127.0.0.1",
                 port: int = 0):
        import http.server

        self.router = router
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            timeout = 30.0

            def do_GET(self):  # noqa: N802 (stdlib casing)
                server._get(self)

            def do_POST(self):  # noqa: N802
                server._post(self)

            def log_message(self, *args):
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="att-router", daemon=True
        )
        self._thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5.0)

    # -- handlers (each runs on its own daemon thread) ----------------------

    @staticmethod
    def _read_json(handler) -> dict:
        n = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(n) if n else b"{}"
        return json.loads(body or b"{}")

    @staticmethod
    def _send_json(handler, payload: dict, status: int = 200):
        body = json.dumps(payload).encode()
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _get(self, handler):
        if handler.path == "/v1/placement":
            self._send_json(handler, {"placement": self.router.placement()})
        elif handler.path == "/v1/kv/directory":
            self._send_json(handler, self.router.kv_directory())
        elif handler.path in ("/metrics", "/"):
            # ride THE exposition renderer (telemetry/exporter) through a
            # rollup shim, not a hand-rolled formatter: name sanitization
            # and format fixes must live in exactly one place
            from ..telemetry.exporter import prometheus_text

            body = prometheus_text(_RouterMetricsSession(self.router)).encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        else:
            handler.send_error(404)

    def _post(self, handler):
        try:
            body = self._read_json(handler)
        except ValueError:
            handler.send_error(400, "bad json")
            return
        if handler.path == "/v1/register":
            self.router.register_replica(body["name"], body["url"])
            self._send_json(handler, {"ok": True})
        elif handler.path == "/v1/deregister":
            known = self.router.deregister_replica(body.get("name", ""))
            self._send_json(handler, {"ok": True, "known": known})
        elif handler.path == "/v1/submit":
            self._submit(handler, body)
        else:
            handler.send_error(404)

    def _submit(self, handler, body: dict):
        handler.send_response(200)
        handler.send_header("Content-Type", "application/jsonl")
        handler.end_headers()
        client_gone = []

        def emit(evt: dict):
            # a vanished client must not read as a REPLICA failure (the
            # hop keeps finishing replica-side); swallow and stop writing
            if client_gone:
                return
            try:
                handler.wfile.write((json.dumps(evt) + "\n").encode())
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                client_gone.append(True)

        def on_token(token, req):
            emit({"event": "token", "i": len(req.tokens) - 1, "token": token,
                  "request_id": req.id})

        req = self.router.submit(
            [int(t) for t in body.get("prompt") or []],
            max_new_tokens=int(body.get("max_new_tokens") or 32),
            seed=int(body.get("seed") or 0),
            session=body.get("session"),
            tenant=str(body.get("tenant") or "default"),
            priority=int(body.get("priority") or 0),
            request_id=body.get("request_id"),
            timeout_s=body.get("timeout_s"),
            on_token=on_token,
        )
        emit({
            "event": "done", "request_id": req.id,
            "outcome": req.outcome, "finish_reason": req.finish_reason,
            "shed_reason": req.shed_reason, "replica": req.replica,
            "requeues": sum(1 for h in req.hops if "error" in h),
            "hops": req.hops, "tokens": req.tokens,
            "prefix_hit": req.prefix_hit,
        })
