"""Native host runtime: C++ primitives (csrc/att_runtime.cpp) behind
graceful Python fallbacks. See native.py for the build/load protocol."""

from .native import native_available, parallel_memcpy, parallel_read_segments
from .prefetch import HostPrefetcher, RingBuffer

__all__ = [
    "native_available",
    "parallel_memcpy",
    "parallel_read_segments",
    "HostPrefetcher",
    "RingBuffer",
]
