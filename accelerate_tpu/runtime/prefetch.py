"""Host-side prefetch: assemble the next batch while the device computes.

`RingBuffer` wraps the native slots/condvar ring (csrc) with a pure-Python
fallback; `HostPrefetcher` runs a producer thread that pulls from any
iterator, assembles each batch into a ring slot with GIL-free parallel
memcpy, and (optionally) starts the host->device transfer so the train
loop's `next()` returns an already-in-flight batch.

This replaces the torch DataLoader's worker-process machinery (reference
data_loader.py leans on torch's C++ loader): JAX needs the batch as one
contiguous host buffer per step, which is exactly what the ring provides.
"""

from __future__ import annotations

import ctypes
import queue
import threading
from typing import Callable, Iterator, Optional

import numpy as np

from .native import _get_lib, parallel_memcpy


class RingBuffer:
    """Fixed-size slot ring (producer/consumer). Native-backed when built."""

    def __init__(self, slots: int, slot_bytes: int):
        self.slots = slots
        self.slot_bytes = slot_bytes
        self._lib = _get_lib()
        if self._lib is not None:
            self._ring = self._lib.att_ring_create(slots, slot_bytes)
            self._buffers = None
        else:
            self._ring = None
            self._buffers = [np.empty(slot_bytes, np.uint8) for _ in range(slots)]
            self._state = [0] * slots  # 0 free, 2 ready
            self._fill_cursor = 0
            self._read_cursor = 0
            self._closed = False
            self._cond = threading.Condition()

    # -- producer ---------------------------------------------------------
    def acquire_fill(self) -> int:
        if self._ring is not None:
            return self._lib.att_ring_acquire_fill(self._ring)
        with self._cond:
            slot = self._fill_cursor
            self._cond.wait_for(lambda: self._closed or self._state[slot] == 0)
            if self._closed:
                return -1
            self._state[slot] = 1
            self._fill_cursor = (slot + 1) % self.slots
            return slot

    def commit_fill(self, slot: int) -> None:
        if self._ring is not None:
            self._lib.att_ring_commit_fill(self._ring, slot)
            return
        with self._cond:
            self._state[slot] = 2
            self._cond.notify_all()

    # -- consumer ---------------------------------------------------------
    def acquire_read(self) -> int:
        if self._ring is not None:
            return self._lib.att_ring_acquire_read(self._ring)
        with self._cond:
            slot = self._read_cursor
            self._cond.wait_for(lambda: self._closed or self._state[slot] == 2)
            if self._state[slot] != 2:
                return -1
            self._state[slot] = 3
            self._read_cursor = (slot + 1) % self.slots
            return slot

    def release_read(self, slot: int) -> None:
        if self._ring is not None:
            self._lib.att_ring_release_read(self._ring, slot)
            return
        with self._cond:
            self._state[slot] = 0
            self._cond.notify_all()

    def slot_view(self, slot: int) -> np.ndarray:
        """uint8 view of a slot's storage (zero-copy)."""
        if self._ring is not None:
            ptr = self._lib.att_ring_slot_ptr(self._ring, slot)
            return np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), shape=(self.slot_bytes,)
            )
        return self._buffers[slot]

    def close(self) -> None:
        if self._ring is not None:
            self._lib.att_ring_close(self._ring)
            return
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __del__(self):
        try:
            if getattr(self, "_ring", None) is not None:
                self._lib.att_ring_destroy(self._ring)
                self._ring = None
        except Exception:
            pass


class HostPrefetcher:
    """Iterator wrapper: a producer thread keeps ``depth`` assembled batches
    ahead of the consumer.

    Each source item must be a dict of numpy arrays with fixed shapes
    (static-shape contract of the jit step). ``transform`` (e.g.
    make_global_batch for device placement) runs on the consumer side.
    """

    def __init__(
        self,
        source: Iterator,
        depth: int = 2,
        transform: Optional[Callable] = None,
        copy_threads: int = 4,
    ):
        self.source = iter(source)
        self.depth = max(2, depth)
        self.transform = transform
        self.copy_threads = copy_threads
        self._ring: Optional[RingBuffer] = None
        self._layout = None  # [(key, shape, dtype, byte_offset, nbytes)]
        self._slot_bytes = 0
        self._out: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()

    def _init_layout(self, first) -> None:
        offset = 0
        layout = []
        if isinstance(first, dict):
            for key in sorted(first):
                arr = np.asarray(first[key])
                if arr.dtype == object:
                    layout = []
                    offset = 0
                    break
                layout.append((key, arr.shape, arr.dtype, offset, arr.nbytes))
                offset += (arr.nbytes + 63) // 64 * 64  # 64B-align each field
        self._layout = layout
        self._slot_bytes = max(offset, 64)
        self._ring = RingBuffer(self.depth, self._slot_bytes)
        # side-channel for batches that don't match the layout (e.g. the
        # ragged final batch): carried as objects, ring slot left untouched
        self._slot_objects = [None] * self.depth

    def _matches_layout(self, batch) -> bool:
        if not self._layout or not isinstance(batch, dict):
            return False
        if set(batch) != {k for k, *_ in self._layout}:
            return False
        return all(
            batch[key].shape == shape and batch[key].dtype == dtype
            for key, shape, dtype, _, _ in self._layout
        )

    def _fill(self, slot: int, batch) -> None:
        if not self._matches_layout(batch):
            self._slot_objects[slot] = batch
            return
        self._slot_objects[slot] = None
        view = self._ring.slot_view(slot)
        dsts, srcs = [], []
        for key, shape, dtype, off, nbytes in self._layout:
            dsts.append(view[off : off + nbytes].view(dtype).reshape(shape))
            srcs.append(np.ascontiguousarray(batch[key], dtype=dtype))
        parallel_memcpy(dsts, srcs, num_threads=self.copy_threads)

    def _producer(self) -> None:
        try:
            for batch in self.source:
                if isinstance(batch, dict):
                    batch = {k: np.asarray(v) for k, v in batch.items()}
                if self._layout is None:
                    self._init_layout(batch)
                    self._started.set()
                slot = self._ring.acquire_fill()
                if slot < 0:
                    return
                self._fill(slot, batch)
                self._ring.commit_fill(slot)
            self._done.set()
            if self._ring is not None:
                self._ring.close()
            self._started.set()
        except Exception as e:  # propagate to consumer
            self._error = e
            self._done.set()
            self._started.set()
            if self._ring is not None:
                self._ring.close()

    def __iter__(self):
        self._error = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        self._started.wait()
        while True:
            if self._ring is None:  # empty source
                break
            slot = self._ring.acquire_read()
            if slot < 0:
                break
            if self._slot_objects[slot] is not None:
                batch = self._slot_objects[slot]
                self._slot_objects[slot] = None
            else:
                view = self._ring.slot_view(slot)
                batch = {}
                for key, shape, dtype, off, nbytes in self._layout:
                    # copy out so the slot can be reused immediately; still
                    # cheaper than Python-side stacking because the producer
                    # did the assembly off-thread
                    batch[key] = view[off : off + nbytes].view(dtype).reshape(shape).copy()
            self._ring.release_read(slot)
            yield self.transform(batch) if self.transform else batch
        if self._error is not None:
            raise self._error

    def close(self):
        if self._ring is not None:
            self._ring.close()
