"""ctypes bindings for csrc/att_runtime.cpp, with build-on-first-use.

Why ctypes and not an extension module: the C library has a pure C ABI
(no Python.h), so one `g++ -O3 -shared -fPIC -pthread` works on any image
with a toolchain and nothing to compile against; ctypes FFI calls release
the GIL, which is the entire point (parallel IO / memcpy while Python
drives the train loop). Every entry point has a numpy fallback so the
framework works unbuilt.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc", "att_runtime.cpp")
_OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_CFLAGS = ["-O3", "-march=native", "-funroll-loops", "-std=c++17", "-shared", "-fPIC", "-pthread"]


def _build() -> Optional[str]:
    # The artifact name embeds the source hash, the compile flags, AND the
    # host CPU's feature flags (the -march=native binary is
    # microarchitecture-specific: a checkout/_build shared across machines
    # of the same arch but different ISA extensions must rebuild, not
    # SIGILL), so a stale or foreign binary can never be picked up: it
    # simply isn't at the expected path and a fresh build runs. _build/ is
    # never committed.
    import platform

    cpu_flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 spells it "flags", aarch64 "Features"
                if line.startswith(("flags", "Features")):
                    cpu_flags = line
                    break
    except OSError:  # pragma: no cover - non-Linux
        pass
    try:
        with open(_SRC, "rb") as f:
            key = hashlib.sha256(
                f.read() + " ".join(_CFLAGS).encode()
                + platform.machine().encode() + cpu_flags.encode()
            ).hexdigest()[:16]
    except OSError as e:  # pragma: no cover - source missing
        logger.warning(f"att_runtime source unreadable ({e}); using Python fallbacks")
        return None
    out = os.path.join(_OUT_DIR, f"libatt_runtime-{key}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_OUT_DIR, exist_ok=True)
    # Compile to a private temp name, then rename into place: the rename is
    # atomic, so concurrent builders (launch --num_processes N on a fresh
    # checkout) or an interrupted g++ can never leave a half-written .so at
    # the path other processes load.
    tmp = f"{out}.tmp.{os.getpid()}"
    cmd = ["g++", *_CFLAGS, _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except Exception as e:  # pragma: no cover - no toolchain
        logger.warning(f"att_runtime native build failed ({e}); using Python fallbacks")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB is not None or _LIB_TRIED:
        return _LIB
    with _LIB_LOCK:
        if _LIB is not None or _LIB_TRIED:
            return _LIB
        _LIB_TRIED = True
        if os.environ.get("ACCELERATE_TPU_DISABLE_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        ucpp = ctypes.POINTER(ctypes.c_void_p)
        lib.att_parallel_read.argtypes = [ctypes.c_char_p, u64p, u64p, ucpp, ctypes.c_int, ctypes.c_int]
        lib.att_parallel_read.restype = ctypes.c_int
        lib.att_parallel_memcpy.argtypes = [ucpp, ucpp, u64p, ctypes.c_int, ctypes.c_int]
        lib.att_parallel_memcpy.restype = None
        lib.att_ring_create.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.att_ring_create.restype = ctypes.c_void_p
        for name, argtypes, restype in [
            ("att_ring_destroy", [ctypes.c_void_p], None),
            ("att_ring_close", [ctypes.c_void_p], None),
            ("att_ring_acquire_fill", [ctypes.c_void_p], ctypes.c_int),
            ("att_ring_commit_fill", [ctypes.c_void_p, ctypes.c_int], None),
            ("att_ring_acquire_read", [ctypes.c_void_p], ctypes.c_int),
            ("att_ring_release_read", [ctypes.c_void_p, ctypes.c_int], None),
            ("att_ring_slot_ptr", [ctypes.c_void_p, ctypes.c_int], ctypes.c_void_p),
            ("att_ring_slot_bytes", [ctypes.c_void_p], ctypes.c_uint64),
            (
                "att_quantize_group",
                [ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
                 ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
                 ctypes.c_void_p, ctypes.c_int],
                ctypes.c_int,
            ),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = argtypes
            fn.restype = restype
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return _get_lib() is not None


def _as_u64_array(values: Sequence[int]):
    return (ctypes.c_uint64 * len(values))(*values)


def _as_ptr_array(buffers) -> "ctypes.Array":
    arr = (ctypes.c_void_p * len(buffers))()
    for i, b in enumerate(buffers):
        arr[i] = b.ctypes.data if isinstance(b, np.ndarray) else ctypes.cast(b, ctypes.c_void_p)
    return arr


def parallel_read_segments(
    path: str,
    offsets: Sequence[int],
    dests: Sequence[np.ndarray],
    num_threads: int = 8,
) -> None:
    """Read len(offsets) byte segments of ``path`` into the (1-D uint8 or
    contiguous) ``dests`` arrays; segment i has size dests[i].nbytes."""
    sizes = [int(d.nbytes) for d in dests]
    lib = _get_lib()
    if lib is None:
        with open(path, "rb") as f:
            for off, dst in zip(offsets, dests):
                f.seek(off)
                buf = f.read(dst.nbytes)
                flat = dst.reshape(-1).view(np.uint8)
                flat[:] = np.frombuffer(buf, np.uint8)
        return
    rc = lib.att_parallel_read(
        path.encode(),
        _as_u64_array(list(offsets)),
        _as_u64_array(sizes),
        ctypes.cast(_as_ptr_array(list(dests)), ctypes.POINTER(ctypes.c_void_p)),
        len(dests),
        num_threads,
    )
    if rc != 0:
        raise OSError(f"att_parallel_read({path}) failed with code {rc}")


def parallel_memcpy(dests: Sequence[np.ndarray], srcs: Sequence[np.ndarray], num_threads: int = 8) -> None:
    """Copy srcs[i] -> dests[i] (same nbytes) on native threads."""
    assert len(dests) == len(srcs)
    sizes = []
    for d, s in zip(dests, srcs):
        if d.nbytes != s.nbytes:
            raise ValueError(f"size mismatch {d.nbytes} != {s.nbytes}")
        sizes.append(int(d.nbytes))
    lib = _get_lib()
    if lib is None:
        for d, s in zip(dests, srcs):
            np.copyto(d.reshape(-1).view(np.uint8), np.ascontiguousarray(s).reshape(-1).view(np.uint8))
        return
    srcs = [np.ascontiguousarray(s) for s in srcs]
    lib.att_parallel_memcpy(
        ctypes.cast(_as_ptr_array(list(dests)), ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(_as_ptr_array(srcs), ctypes.POINTER(ctypes.c_void_p)),
        _as_u64_array(sizes),
        len(dests),
        num_threads,
    )


def quantize_group_native(w: np.ndarray, group: int, bits: int, nf4: bool):
    """Single-pass per-group quantization of a [K, ...] array along dim 0 in
    C (see csrc att_quantize_group). Returns (packed int8 data, fp32 scales)
    with the same layout utils/quantization.quantize_array_host produces, or
    None when the native library / dtype / layout can't serve the request
    (caller falls back to numpy). The C call releases the GIL, so a loader
    thread can overlap quantization with async device transfers."""
    lib = _get_lib()
    if lib is None:
        return None
    if w.ndim < 1:
        return None
    k = w.shape[0]
    n = int(np.prod(w.shape[1:])) if w.ndim > 1 else 1
    if k == 0 or n == 0 or k % group != 0:
        return None
    if bits == 4 and group % 2 != 0 and k != group:
        return None
    if bits == 4 and k % 2 != 0 and k != group:
        return None
    import ml_dtypes

    if w.dtype == np.float32:
        src_dtype = 0
    elif w.dtype == ml_dtypes.bfloat16:
        src_dtype = 1
    else:
        return None
    w = np.ascontiguousarray(w)
    out_rows = k if bits == 8 else (k + 1) // 2
    out_q = np.empty((out_rows,) + w.shape[1:], np.int8)
    out_scale = np.empty((k // group,) + w.shape[1:], np.float32)
    rc = lib.att_quantize_group(
        w.ctypes.data, src_dtype, k, n, group, bits, 1 if nf4 else 0,
        out_q.ctypes.data, out_scale.ctypes.data, os.cpu_count() or 1,
    )
    if rc != 0:
        return None
    return out_q, out_scale
