"""Big-model inference: load models larger than HBM and run them.

Parity target: /root/reference/src/accelerate/big_modeling.py (633 LoC).
Mechanism swap (SURVEY §7 stage 5):

  reference                         TPU-native
  ---------                         ----------
  meta-device init (monkey-patched  `init_empty_weights` = jax.eval_shape
  register_parameter, :126-167)     over module.init — zero allocation
  infer_auto_device_map over GPUs   greedy fit over HBM/pinned-host/disk
  AlignDevicesHook pre/post forward  XLA streams pinned-host params into
  (D2H/H2D per layer, hooks.py:323)  the jit via in-graph device_put; disk
                                     weights memmap->host per call
  OffloadedWeightsLoader memmap      same design (utils/offload.py)

No wrapper classes, no forward patching: dispatch returns params with
mixed placements and a jitted apply whose transfers the XLA scheduler
overlaps with compute.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .utils.modeling import (
    _DiskWeight,
    _to_pinned_host,
    check_device_map,
    compute_module_sizes,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    placement_of,
)
from .utils.serialization import flatten_pytree, unflatten_to_like


def _maybe_enable_weight_streaming(definition, device_map):
    """If the definition supports per-layer weight streaming
    (``config.stream_layer_weights``) and any params land off-device, turn
    the flag on via a rebuilt definition (flax modules are frozen)."""
    import dataclasses as _dc

    cfg = getattr(definition, "config", None)
    if cfg is None or not hasattr(cfg, "stream_layer_weights"):
        return definition
    tiers = set((device_map or {}).values())
    if not (tiers - {"device"}) or cfg.stream_layer_weights:
        return definition
    try:
        new_cfg = _dc.replace(cfg, stream_layer_weights=True)
        return definition.copy(config=new_cfg) if hasattr(definition, "copy") else _dc.replace(definition, config=new_cfg)
    except Exception:  # definition isn't a plain dataclass module
        return definition


def init_empty_weights(module, *sample_args, rng=None, **sample_kwargs):
    """Abstract (zero-allocation) init: the shapes/dtypes of every variable
    without materializing any (reference init_empty_weights:57 needs a
    meta-device monkey-patch; eval_shape is the JAX-native equivalent).

    Returns a pytree of jax.ShapeDtypeStruct."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    fn = functools.partial(module.init, rng, *sample_args, **sample_kwargs)
    abstract = jax.eval_shape(fn)
    # strip flax Partitioned boxes to plain ShapeDtypeStructs
    from .parallel.sharding import unbox_params

    raw, _ = unbox_params(abstract)
    return raw


class DispatchedModel:
    """Callable returned by dispatch_model: runs the module with
    mixed-placement params. Disk weights load per call (matching reference
    disk-offload semantics); host weights stream into HBM inside the jit."""

    def __init__(self, definition, params, mesh=None, device_map=None, output_device=None):
        self.definition = _maybe_enable_weight_streaming(definition, device_map)
        self.params = params
        self.mesh = mesh
        self.device_map = dict(device_map or {})
        # compiled programs and placement transforms keyed by placement
        # state, so materialize()/offload() ping-pong (CpuOffloadHook
        # pipelines) reuses the compile for each tier layout instead of
        # retracing every promote/demote
        self._jits: dict = {}
        self._placers: dict = {}
        # AOT executables from aot_compile(), keyed by (placement, avals):
        # __call__ uses one directly when the call signature matches
        self._aot: dict = {}
        self._aot_hits = 0

    def _placement_key(self):
        return tuple(sorted(self.device_map.items()))

    # sentinel "shardings" for host-tier params:
    _STREAM = "host_stream"      # model streams this subtree itself (per-layer)
    _TO_DEVICE = "host_to_device"  # in-graph transfer at the jit boundary

    def _target_shardings(self, all_device: bool = False):
        """Per-param placement plan.

        Device-tier params get an explicit device/mesh sharding (an in-jit
        device_put). Host-tier ("cpu"/"disk") params either stay in pinned
        host for the model to stream per-layer inside its scan (paths the
        definition declares via ``host_streamable_prefixes()`` — peak HBM is
        then one layer's weights, the per-layer-streaming capability of
        reference hooks.py:323-390), or get an in-graph host->HBM transfer
        that XLA's latency-hiding scheduler places near the consumer."""
        from .parallel.sharding import infer_param_sharding
        from .utils.dataclasses import ShardingConfig
        from .utils.serialization import flatten_pytree, unflatten_to_like

        abstract = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype),
            self._concrete(self.params),
            is_leaf=lambda l: isinstance(l, _DiskWeight),
        )
        flat = flatten_pytree(abstract)
        if self.mesh is not None:
            device_shardings = flatten_pytree(
                infer_param_sharding(abstract, self.mesh, ShardingConfig())
            )
        else:
            from jax.sharding import SingleDeviceSharding

            from .parallel.sharding import _memory_kind_available

            dev = jax.devices()[0]
            # some backends (older-jax CPU) expose no "device" memory kind;
            # the default placement is then the device memory anyway
            if _memory_kind_available("device"):
                sharding = SingleDeviceSharding(dev, memory_kind="device")
            else:
                sharding = SingleDeviceSharding(dev)
            device_shardings = {k: sharding for k in flat}
        streamable = []
        fn = getattr(self.definition, "host_streamable_prefixes", None)
        if fn is not None:
            streamable = list(fn())
        out = {}
        for path in flat:
            tier = placement_of(path, self.device_map) if self.device_map else "device"
            if all_device or tier == "device":
                out[path] = device_shardings[path]
            elif any(path == p or path.startswith(p + "/") for p in streamable):
                out[path] = self._STREAM
            else:
                out[path] = self._TO_DEVICE
        return unflatten_to_like(out, abstract)

    @staticmethod
    def _concrete(params):
        """Materialize _DiskWeight leaves into (pinned) host memory — not
        HBM; the jit streams them like any other host-tier param."""

        def _mat(leaf):
            if isinstance(leaf, _DiskWeight):
                return _to_pinned_host(leaf.load())
            return leaf

        return jax.tree_util.tree_map(
            _mat, params, is_leaf=lambda l: isinstance(l, _DiskWeight)
        )

    def _apply_for(self, key):
        """(apply, jitted) for the current placement key, built once."""
        if key not in self._jits:
            from .accelerator import _merge_static_call

            placer = self.param_placer()

            def apply(p, a, kw, s_args, s_kw):
                a, kw = _merge_static_call(a, kw, s_args, s_kw)
                return self.definition.apply({"params": placer(p)}, *a, **kw)

            self._jits[key] = (apply, jax.jit(apply, static_argnums=(3, 4)))
        return self._jits[key]

    @staticmethod
    def _aval_key(tree):
        # jnp.shape/result_type, not .shape/.dtype: traced leaves may be
        # Python scalars (ints/floats pass _split_static_call as traced)
        return tuple(
            (jnp.shape(l), str(jnp.result_type(l)))
            for l in jax.tree_util.tree_leaves(tree)
        )

    def _abstract_params(self):
        """ShapeDtypeStructs mirroring what ``_concrete(self.params)`` will
        be at call time: device-tier leaves carry the loader's mesh sharding
        (or stay uncommitted = default device single-chip), host/disk-tier
        leaves are committed to pinned host. Matching the real placements is
        what lets __call__ use the AOT executable instead of retracing."""
        from jax.sharding import SingleDeviceSharding

        flat = flatten_pytree(self.params)
        pinned = None
        dev = jax.local_devices()[0]
        try:
            if any(m.kind == "pinned_host" for m in dev.addressable_memories()):
                pinned = SingleDeviceSharding(dev, memory_kind="pinned_host")
        except Exception:  # pragma: no cover
            pinned = None
        mesh_shardings = None
        if self.mesh is not None:
            from .parallel.sharding import infer_param_sharding
            from .utils.dataclasses import ShardingConfig

            abstract = {
                p: jax.ShapeDtypeStruct(tuple(l.shape), l.dtype) for p, l in flat.items()
            }
            mesh_shardings = flatten_pytree(
                infer_param_sharding(
                    unflatten_to_like(abstract, self.params), self.mesh, ShardingConfig()
                )
            )
        out = {}
        for path, leaf in flat.items():
            tier = placement_of(path, self.device_map) if self.device_map else "device"
            shape, dtype = tuple(leaf.shape), leaf.dtype
            if tier == "device" and mesh_shardings is not None:
                out[path] = jax.ShapeDtypeStruct(shape, dtype, sharding=mesh_shardings[path])
            elif tier == "device" or pinned is None:
                out[path] = jax.ShapeDtypeStruct(shape, dtype)
            else:
                out[path] = jax.ShapeDtypeStruct(shape, dtype, sharding=pinned)
        return unflatten_to_like(out, self.params)

    def _export_cache_path(self, key, aval_key, static_args, static_kw, abstract):
        """Disk path for the serialized jax.export artifact of this AOT
        program, or None when the persistent cache is disabled. The key
        hashes everything the traced program depends on: model definition
        (flax repr includes the config), placements, param avals+shardings,
        call avals, statics, and the jax version."""
        import hashlib

        from .utils.compile_cache import ensure_persistent_compile_cache

        base = ensure_persistent_compile_cache()
        if base is None:
            return None
        from . import __version__ as att_version

        mat = repr((
            jax.__version__,
            # package version: param_placer/dequantize logic is baked into
            # the traced program, so an upgrade must invalidate artifacts
            att_version,
            repr(self.definition),
            key,
            aval_key,
            static_args,
            sorted(static_kw.items()) if isinstance(static_kw, dict) else static_kw,
            [
                (p, str(l.shape), str(l.dtype), str(getattr(l, "sharding", None)))
                for p, l in sorted(flatten_pytree(abstract).items())
            ],
        ))
        h = hashlib.sha256(mat.encode()).hexdigest()[:32]
        d = os.path.join(base, "exports")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"dispatch-{h}.jaxexport")

    def aot_compile(self, *args, **kwargs):
        """Ahead-of-time compile the placed apply for these example args
        (shapes/dtypes only — values ignored). Runs in the calling thread, so
        ``load_checkpoint_and_dispatch`` overlaps it with checkpoint
        streaming; with the persistent compile cache on, the executable also
        serves every later process. Returns self.

        Two-level persistence: the XLA cache skips backend compilation, and a
        ``jax.export`` artifact on disk skips the Python TRACE of the model —
        which is the part a fresh process otherwise pays ~2 s of sole-core
        CPU for during dispatch. A cache-hit process deserializes StableHLO
        and compiles it (hitting the XLA cache), never running model code."""
        from .accelerator import _split_static_call

        traced_args, static_args, traced_kw, static_kw = _split_static_call(args, kwargs)
        key = self._placement_key()
        abstract = self._abstract_params()
        to_aval = lambda t: jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l), jnp.result_type(l)), t
        )
        a_args, a_kw = to_aval(traced_args), to_aval(traced_kw)
        aot_key = (key, self._aval_key((a_args, a_kw)), static_args, static_kw)
        cache_path = self._export_cache_path(
            key, aot_key[1], static_args, static_kw, abstract
        )

        compiled = None
        if cache_path is not None and os.path.exists(cache_path):
            try:
                from jax import export as jax_export

                with open(cache_path, "rb") as f:
                    exp = jax_export.deserialize(bytearray(f.read()))
                # cache the COMPILED AOT object (XLA-cache-served), not the
                # jit wrapper: a wrapper would re-trace on first __call__ and
                # silently recompile on placement drift instead of raising
                # into the documented jit fallback
                compiled = jax.jit(exp.call).lower(abstract, a_args, a_kw).compile()
            except Exception:  # stale/incompatible artifact — retrace below
                compiled = None
        if compiled is None and cache_path is not None:
            # trace ONCE through export: serialize for future processes, and
            # compile this process's executable from the same StableHLO
            try:
                from jax import export as jax_export

                def _bound(p, a, kw):
                    apply, _ = self._apply_for(key)
                    return apply(p, a, kw, static_args, static_kw)

                exp = jax_export.export(jax.jit(_bound))(abstract, a_args, a_kw)
                tmp = cache_path + f".tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(exp.serialize())
                os.replace(tmp, cache_path)
                compiled = jax.jit(exp.call).lower(abstract, a_args, a_kw).compile()
            except Exception:  # best-effort: export has feature gaps
                compiled = None
        if compiled is None:
            _, jitted = self._apply_for(key)
            compiled = jitted.lower(abstract, a_args, a_kw, static_args, static_kw).compile()
        # params avals are excluded from the key: they are determined by the
        # placement key, and walking every param leaf per call would put
        # O(num_params) Python work on the dispatch hot path; a placement
        # drift surfaces as TypeError/ValueError and falls back to jit
        self._aot[aot_key] = compiled
        from .telemetry import current_session

        session = current_session()
        if session is not None and getattr(session, "costs", None) is not None:
            session.costs.capture("dispatch_forward", compiled)
        return self

    def __call__(self, *args, **kwargs):
        # bool/str/None inputs go in as jit statics (Python control flow in
        # flax modules); same partition the TrainEngine uses.
        from .accelerator import _split_static_call

        params = self._concrete(self.params)
        traced_args, static_args, traced_kw, static_kw = _split_static_call(args, kwargs)
        key = self._placement_key()
        apply, jitted = self._apply_for(key)
        try:
            hash((static_args, static_kw))
        except TypeError:
            return apply(params, traced_args, traced_kw, static_args, static_kw)
        aot = None
        if self._aot:  # skip the key build entirely for non-AOT users
            aot = self._aot.get((key, self._aval_key((traced_args, traced_kw)),
                                 static_args, static_kw))
        if aot is not None:
            try:
                out = aot(params, traced_args, traced_kw)
                self._aot_hits += 1
                return out
            except (TypeError, ValueError):  # placement drifted from the AOT avals
                pass
        from .telemetry import forensics

        # the jit fallback is where AOT misses silently recompile — the
        # classic "dispatch was fast once, slow forever after a reshape"
        forensics.note_call(
            "dispatch_forward",
            {"args": traced_args, "kwargs": traced_kw,
             "statics": (static_args, static_kw)},
        )
        return jitted(params, traced_args, traced_kw, static_args, static_kw)

    def param_placer(self):
        """In-graph placement transform used by this model's jit (and by
        generation): device-tier leaves pin to their sharding, non-streamable
        host leaves transfer at the jit boundary, streamable subtrees stay in
        pinned host for the model's per-layer streaming, and quantized
        weights dequantize in-graph (fused into consumers).

        Cached per placement state so repeat calls (and generation's jitted
        loops, which key on placer identity) reuse compiled programs until
        the device_map actually changes."""
        from .utils.quantization import dequantize_params

        key = self._placement_key()
        cached = self._placers.get(key)
        if cached is not None:
            return cached

        shardings = self._target_shardings()
        stream = self._STREAM

        from .parallel.sharding import device_memory_space

        device_space = device_memory_space()

        def _place(leaf, sh):
            if isinstance(sh, str):
                if sh == stream:
                    return leaf
                if device_space is None:
                    return jax.device_put(leaf, jax.local_devices()[0])
                return jax.device_put(leaf, device_space)
            return jax.device_put(leaf, sh)

        def placer(p):
            p = jax.tree_util.tree_map(_place, p, shardings)
            return dequantize_params(p)

        self._placers[key] = placer
        return placer

    def materialize(self):
        """Force all params into device memory (drops offload tiers).
        No-op when already fully on device — a hooked pipeline calls this
        every forward; the compiled program for each placement state is
        cached (``_jits``/``_placers``), so ping-ponging between tiers does
        not retrace."""
        if self.device_map == {"": "device"}:
            return self
        params = self._concrete(self.params)
        shardings = self._target_shardings(all_device=True)
        params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        self.params = params
        self.device_map = {"": "device"}
        return self

    def offload(self):
        """Demote every param back to pinned host memory (the inverse of
        materialize; the CpuOffloadHook mechanism below relies on it)."""
        if self.device_map == {"": "cpu"}:
            return self
        params = self._concrete(self.params)
        self.params = jax.tree_util.tree_map(
            lambda p: _to_pinned_host(np.asarray(jax.device_get(p))), params
        )
        self.device_map = {"": "cpu"}
        return self


def dispatch_model(
    definition,
    params,
    device_map: Mapping[str, str],
    mesh=None,
    offload_folder: Optional[str] = None,
) -> DispatchedModel:
    """Place concrete params per ``device_map`` and return a runnable
    (reference dispatch_model:306). Params already on the right tier are
    left alone."""
    from .utils.modeling import _to_pinned_host
    from .utils.offload import offload_state_dict

    check_device_map(params, device_map)
    flat = flatten_pytree(params)
    disk_dict = {}
    out = {}
    for path, leaf in flat.items():
        tier = placement_of(path, device_map)
        if isinstance(leaf, _DiskWeight):
            out[path] = leaf  # already offloaded
            continue
        if tier == "device":
            out[path] = leaf  # device placement happens in the jit
        elif tier == "cpu":
            out[path] = _to_pinned_host(np.asarray(leaf))
        else:
            name = path.replace("/", ".")
            value = np.asarray(leaf)
            disk_dict[name] = value
            out[path] = _DiskWeight(name, offload_folder, tuple(value.shape), value.dtype)
    if disk_dict:
        if offload_folder is None:
            raise ValueError("device_map places weights on disk but no offload_folder given")
        offload_state_dict(offload_folder, disk_dict)
    placed = unflatten_to_like(out, params)
    return DispatchedModel(definition, placed, mesh=mesh, device_map=device_map)


def cpu_offload(definition, params, mesh=None) -> DispatchedModel:
    """Everything in pinned host RAM, streamed per call (reference :170)."""
    return dispatch_model(definition, params, {"": "cpu"}, mesh=mesh)


def disk_offload(definition, params, offload_folder: str, mesh=None) -> DispatchedModel:
    """Everything on disk (reference :260)."""
    return dispatch_model(definition, params, {"": "disk"}, mesh=mesh, offload_folder=offload_folder)


class CpuOffloadHook:
    """Handle returned by cpu_offload_with_hook: lets pipelines of models
    share HBM by explicitly demoting a model when the next one runs
    (reference UserCpuOffloadHook, big_modeling.py:199-258)."""

    def __init__(self, model: DispatchedModel, prev_hook: "CpuOffloadHook | None" = None):
        self.model = model
        self.prev_hook = prev_hook

    def pre_forward(self):
        if self.prev_hook is not None:
            self.prev_hook.offload()
        self.model.materialize()

    def offload(self):
        self.model.offload()


class _HookedModel:
    """Wraps a DispatchedModel so each call promotes this model's weights
    (and demotes the previous pipeline stage's) before running."""

    def __init__(self, model: DispatchedModel, hook: CpuOffloadHook):
        self._model = model
        self.hook = hook

    def __call__(self, *args, **kwargs):
        self.hook.pre_forward()
        return self._model(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._model, name)


def load_and_quantize_model(
    definition,
    weights,
    quantization_config,
    device_map: Any = None,
    offload_folder: Optional[str] = None,
    mesh=None,
) -> DispatchedModel:
    """Quantize a model's weights to int8/int4 and return a runnable
    (reference utils/bnb.py:44 load_and_quantize_model). ``weights`` is a
    params pytree or a checkpoint path; quantized tensors live on device in
    their packed form and dequantize in-graph per call."""
    from .utils.quantization import quantize_params
    from .utils.serialization import load_flat_dict, unflatten_to_like

    if isinstance(weights, str) or hasattr(weights, "__fspath__"):
        flat = load_flat_dict(str(weights))
        params = {k: jnp.asarray(v) for k, v in flat.items()}
        # checkpoint keys are flat paths; rebuild nesting
        nested: dict = {}
        for key, val in params.items():
            node = nested
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = val
        params = nested
    else:
        params = weights
    qparams = quantize_params(params, quantization_config)
    dm = device_map if isinstance(device_map, dict) else {"": "device"}
    return dispatch_model(definition, qparams, dm, mesh=mesh, offload_folder=offload_folder)


def cpu_offload_with_hook(definition, params, mesh=None, prev_module_hook: CpuOffloadHook | None = None):
    """Keep the model in pinned host RAM; promote it to HBM on call and give
    the caller a hook to demote it again (reference cpu_offload_with_hook:199:
    the pipeline pattern — running stage N+1 offloads stage N). Returns
    ``(model, hook)``."""
    dispatched = cpu_offload(definition, params, mesh=mesh)
    hook = CpuOffloadHook(dispatched, prev_hook=prev_module_hook)
    return _HookedModel(dispatched, hook), hook


def load_checkpoint_and_dispatch(
    definition,
    checkpoint: str,
    *sample_args,
    device_map: Any = "auto",
    max_memory: Optional[dict] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    mesh=None,
    rng=None,
    precompile: bool = True,
    quantization_config=None,
    **sample_kwargs,
) -> DispatchedModel:
    """Abstract-init -> auto device map -> stream checkpoint weights straight
    to their tier (reference load_checkpoint_and_dispatch:504; device-bound
    weights never make a full-model host copy).

    With ``precompile`` (default), the forward program for ``sample_args`` is
    XLA-compiled on a background thread *while* the checkpoint streams from
    disk to its tiers — compile time hides under I/O instead of adding to
    time-to-first-token, and the persistent compile cache makes it a one-time
    cost across processes.

    With ``quantization_config`` (the reference's from_pretrained
    load_in_8bit integration), eligible weights quantize ON THE HOST as they
    stream off disk, so only packed int8/int4 bytes + scales cross the
    host->device link and HBM holds the packed form; dequant fuses into the
    consuming matmuls in-graph."""
    from .utils.compile_cache import ensure_persistent_compile_cache

    ensure_persistent_compile_cache()
    abstract = init_empty_weights(definition, *sample_args, rng=rng, **sample_kwargs)
    abstract_params = abstract["params"] if isinstance(abstract, dict) and "params" in abstract else abstract
    if isinstance(device_map, str):
        if device_map in ("auto", "balanced", "balanced_low_0", "sequential"):
            budget_tree = abstract_params
            if quantization_config is not None:
                # budget with PACKED sizes so quantization actually helps a
                # model FIT (the load_in_8bit purpose): QuantizedWeight
                # nodes flatten to their int8 data + scale leaves, which is
                # exactly the bytes that will occupy HBM
                from .utils.quantization import quantize_abstract_tree

                budget_tree = quantize_abstract_tree(abstract_params, quantization_config)
            device_map = infer_auto_device_map(
                budget_tree,
                max_memory=max_memory,
                # a global dtype override would mis-scale the int8 leaves
                dtype=None if quantization_config is not None else dtype,
                mode=device_map,
            )
        else:
            device_map = {"": device_map}

    model = None
    compile_thread = None
    compile_err: list = []
    if precompile and sample_args:
        # the dispatched apply's input avals depend only on shapes/placements,
        # both known before any weight bytes move — compile concurrently.
        # Dtypes come from the checkpoint HEADER (a bf16 checkpoint loads as
        # bf16 regardless of the model's init dtype), with the explicit
        # ``dtype`` override applied the same way the loader applies it.
        from .utils.quantization import quantize_abstract_tree
        from .utils.serialization import peek_flat_structs

        peeked = peek_flat_structs(checkpoint) or {}

        def _header_dtype(path, leaf):
            out_dtype = peeked.get(path, leaf).dtype
            if dtype is not None and jnp.issubdtype(out_dtype, jnp.floating):
                out_dtype = dtype
            return out_dtype

        cast_abstract = quantize_abstract_tree(
            abstract_params,
            quantization_config,
            placement=lambda p: placement_of(p, device_map) == "device",
            leaf_dtype=_header_dtype,
        )
        model = DispatchedModel(definition, cast_abstract, mesh=mesh, device_map=device_map)
        import threading

        def _compile():
            try:
                model.aot_compile(*sample_args, **sample_kwargs)
            except Exception as e:  # pragma: no cover - AOT is best-effort
                compile_err.append(e)

        def _timed_compile():
            from .utils.phases import phase

            with phase("aot_compile_thread"):
                _compile()

        compile_thread = threading.Thread(target=_timed_compile, daemon=True)
        compile_thread.start()

    from .utils.phases import phase

    with phase("weight_stream_total"):
        params = load_checkpoint_in_model(
            abstract_params,
            checkpoint,
            device_map=device_map,
            offload_folder=offload_folder,
            dtype=dtype,
            mesh=mesh,
            quantization_config=quantization_config,
        )
    if compile_thread is not None:
        with phase("aot_join_wait"):
            compile_thread.join()
    if model is not None and not compile_err:
        model.params = params
        return model
    return DispatchedModel(definition, params, mesh=mesh, device_map=device_map)
