"""The Accelerator façade + TrainEngine (the jit-fused training core).

Parity target: /root/reference/src/accelerate/accelerator.py (3,562 LoC).
The reference keeps the torch eager loop and interposes wrappers (DDP, AMP
autocast, GradScaler). Here the same *user loop shape*

    model, optimizer, dataloader, scheduler = accelerator.prepare(...)
    for batch in dataloader:
        with accelerator.accumulate(model):
            outputs = model(**batch)
            accelerator.backward(outputs["loss"])
            optimizer.step(); scheduler.step(); optimizer.zero_grad()

is executed by staging onto XLA:

- ``model(**batch)`` runs ONE fused jit computing outputs AND gradients
  (grads stashed for the coming ``backward``) — same FLOPs as torch's
  fwd+bwd, no eager/grad-tape machinery;
- ``backward`` folds the stashed grads into the accumulation buffer
  (scaled 1/num_steps — the reference divides the loss instead,
  accelerator.py:2186);
- ``optimizer.step()`` applies one fused optax update (grad-clip + fp16
  loss-scale handling via lax.cond inside the jit);
- data-parallel gradient reduction is IMPLICIT: params are replicated /
  sharded over the mesh and the batch is sharded on dim0, so XLA inserts
  the psum over ICI — there is no DDP bucket machinery to configure.

For peak performance `accelerator.build_train_step(loss_fn)` fuses the whole
micro-batch loop (lax.scan) + update into a single XLA computation.
"""

from __future__ import annotations

import contextlib
import functools
import math
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .data import DataLoaderDispatcher, DataLoaderShard, prepare_data_loader, skip_first_batches as _skip_first_batches
from .logging import get_logger
from .optimizer import AcceleratedOptimizer
from .parallel.sharding import (
    batch_spec,
    infer_param_sharding,
    replicate,
    shard_params,
    sharding_of,
    unbox_params,
)
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import (
    AutocastKwargs,
    CompilePlugin,
    DataLoaderConfiguration,
    GradScalerKwargs,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    KwargsHandler,
    MixedPrecisionConfig,
    PrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
    ShardingConfig,
)
from .utils.operations import (
    convert_outputs_to_fp32,
    convert_to_fp32,
    gather,
    gather_object,
    pad_across_processes,
    recursively_apply,
    reduce,
    send_to_device,
)
from .utils.random import default_keychain

logger = get_logger(__name__)


def _is_flax_module(obj) -> bool:
    try:
        import flax.linen as nn

        return isinstance(obj, nn.Module)
    except Exception:
        return False


def _default_loss_selector(outputs):
    """Find the scalar loss in model outputs (dict['loss'] / .loss / scalar /
    first element of a tuple)."""
    if isinstance(outputs, jax.Array) and outputs.ndim == 0:
        return outputs
    if isinstance(outputs, dict) and "loss" in outputs:
        return outputs["loss"]
    if hasattr(outputs, "loss"):
        return outputs.loss
    if isinstance(outputs, (tuple, list)) and len(outputs) > 0:
        return outputs[0]
    raise ValueError(
        "Could not locate a scalar loss in the model outputs; return a dict "
        "with a 'loss' key (or a scalar), or pass loss_fn= to prepare()."
    )


class Model:
    """Bundles a model definition with its variables — the unit `prepare()`
    accepts (torch modules carry params internally; JAX separates them).

    ``definition`` is either a flax linen Module or a pure
    ``apply(params, *args, **kwargs)`` callable. ``variables`` for flax is
    the full variables dict ({'params': ..., possibly 'batch_stats': ...});
    for a callable it is the params pytree itself.
    """

    def __init__(self, definition, variables, loss_fn: Optional[Callable] = None):
        self.definition = definition
        self.is_flax = _is_flax_module(definition)
        if self.is_flax and not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        self.variables = variables
        self.loss_fn = loss_fn

    @property
    def params(self):
        return self.variables["params"] if self.is_flax else self.variables

    @property
    def extra_collections(self) -> dict:
        if not self.is_flax:
            return {}
        return {k: v for k, v in self.variables.items() if k != "params"}


class PreparedModel:
    """What `prepare(model)` returns: callable like the original, running the
    fused forward(+grad) jit. ``train()``/``eval()`` toggle gradient
    computation and mutable-state updates (torch-parity)."""

    def __init__(self, engine: "TrainEngine"):
        self._engine = engine
        self.training = True

    def __call__(self, *args, **kwargs):
        return self._engine.model_call(self.training, *args, **kwargs)

    def train(self, mode: bool = True):
        self.training = mode
        return self

    def eval(self):
        self.training = False
        return self

    @property
    def params(self):
        return self._engine.params

    @property
    def variables(self):
        return self._engine.current_variables()

    def state_dict(self):
        return self._engine.current_variables()

    def unwrap(self) -> Model:
        m = Model(self._engine.model.definition, self._engine.current_variables(),
                  loss_fn=self._engine.model.loss_fn)
        return m


def _roll_fp8_stats(extra_state):
    """Advance the delayed-fp8 amax histories one optimizer step (forwards
    max-accumulate into the current slot; the engine rolls the slot HERE so
    accumulation microsteps / pipeline ticks share one slot and the window
    spans real steps — TE's per-iteration roll). No-op without a live
    "fp8_stats" collection. Callers must NOT roll on paths that cannot
    record amaxes (a user loss_fn cannot update mutable collections — its
    forwards discard the writes, and rolling anyway would drain a restored
    history to zeros within history_len steps)."""
    from collections.abc import Mapping

    if isinstance(extra_state, Mapping) and "fp8_stats" in extra_state:
        from .ops.fp8 import roll_amax_histories

        return {
            **extra_state,
            "fp8_stats": roll_amax_histories(extra_state["fp8_stats"]),
        }
    return extra_state


def _make_scale_state(kwargs: GradScalerKwargs) -> dict:
    """Dynamic loss scale (GradScaler analog) as a device pytree."""
    return {
        "scale": jnp.asarray(kwargs.init_scale, jnp.float32),
        "growth_tracker": jnp.asarray(0, jnp.int32),
    }


class TrainEngine:
    """Owns the device state (params/opt_state/accum grads/loss scale) and
    the jitted computations for one model+optimizer pair."""

    def __init__(
        self,
        model: Model,
        accelerator: "Accelerator",
    ):
        self.model = model
        self.accelerator = accelerator
        self.state = accelerator.state
        self.mesh = accelerator.state.mesh
        self.precision: MixedPrecisionConfig = accelerator.state.precision
        self.sharding_config: ShardingConfig = accelerator.state.sharding_config
        self.gradient_state = accelerator.gradient_state

        # --- shard parameters over the mesh (the FSDP/DDP-wrap analog) ---
        raw_params, logical_axes = unbox_params(model.params)
        self.param_sharding = infer_param_sharding(
            raw_params, self.mesh, self.sharding_config, logical_axes
        )
        if self.sharding_config.offload_params_to_host:
            # FSDP cpu_offload analog: master params live in pinned host;
            # every compute path streams them to HBM in-graph (_cast_params).
            # Scalar params stay on device (rank-0 placement rejected by SPMD).
            from .parallel.sharding import with_memory_kind

            self.param_sharding = jax.tree_util.tree_map(
                lambda sh, p: with_memory_kind(sh, "pinned_host") if getattr(p, "ndim", 0) >= 1 else sh,
                self.param_sharding,
                raw_params,
            )
        with jax.transfer_guard("allow"):
            self.params = shard_params(
                jax.tree_util.tree_map(
                    lambda p: jnp.asarray(p, self.precision.param_dtype)
                    if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)
                    else jnp.asarray(p),
                    raw_params,
                ),
                self.param_sharding,
            )
        self.extra_state = replicate(model.extra_collections, self.mesh) if model.extra_collections else {}

        self.optimizer: Optional[optax.GradientTransformation] = None
        self.opt_state = None
        self.schedule: Optional[Callable] = None
        self.step_count = 0
        self._accum_grads = None
        self._accum_finite = None
        self._pending_grads = None
        self._pending_loss = None
        self._last_skipped = False
        self._clip_max_norm = None
        self.scale_state = (
            _make_scale_state(self.precision.grad_scaler)
            if self.precision.needs_loss_scaling
            else None
        )
        self.loss_fn = model.loss_fn or _default_loss_selector
        self._jit_cache: dict = {}
        self.donate_state = accelerator.compile_plugin.donate_state
        # telemetry session (set by Accelerator.prepare when enabled); the
        # step paths guard on `is not None` so disabled runs pay one check
        self.telemetry = None
        self._pipeline_fallback_warned = False
        # models can own their backward schedule (DecoderLM 1f1b pipeline:
        # interleaved per-microbatch fwd/bwd that reverse-mode AD cannot
        # express). Only usable when the loss comes from the model itself —
        # a user loss_fn would be silently ignored by the manual path.
        self._manual_vag = None
        self._manual_vag_wants_rng = False
        # model call-signature facts, resolved once: positional parameter
        # order (binds tuple batches by NAME in _extract_lm_batch) and
        # whether training should default flax `deterministic` to False —
        # only when the config actually carries dropout, so models without
        # it keep bit-identical traces
        self._call_argnames = ("input_ids", "labels")
        self._train_dropout_default = False
        self._det_argpos = -1
        if model.is_flax:
            import inspect

            try:
                sig_params = inspect.signature(model.definition.__call__).parameters
                self._call_argnames = tuple(sig_params)
                self._train_dropout_default = (
                    "deterministic" in sig_params
                    and getattr(
                        getattr(model.definition, "config", None), "dropout_rate", 0
                    )
                    > 0
                )
                if self._train_dropout_default:
                    self._det_argpos = self._call_argnames.index("deterministic")
            except (TypeError, ValueError):
                pass
        if model.loss_fn is None:
            getter = getattr(model.definition, "pipeline_value_and_grad", None)
            if getter is not None:
                self._manual_vag = getter()
                # dropout models need the per-step key threaded into the
                # schedule (per-(stage, microbatch) masks); gate on BOTH the
                # config needing it and the hook's signature accepting it, so
                # duck-typed hooks without an rng parameter keep working
                import inspect

                wants = (
                    getattr(getattr(model.definition, "config", None), "dropout_rate", 0) > 0
                )
                if wants:
                    hook_takes_rng = False
                    try:
                        hook_takes_rng = "rng" in inspect.signature(self._manual_vag).parameters
                    except (TypeError, ValueError):
                        pass
                    if not hook_takes_rng:
                        # the AD path would train WITH dropout for this
                        # config, so an rng-less duck-typed hook silently
                        # toggles regularization per-batch-routing (ADVICE r5)
                        logger.warning(
                            "model config has dropout_rate > 0 but its "
                            "pipeline_value_and_grad hook accepts no 'rng' "
                            "parameter: batches routed through the manual "
                            "pipeline schedule will train WITHOUT dropout. "
                            "Add an `rng=` kwarg to the hook to receive the "
                            "per-step dropout key."
                        )
                    wants = hook_takes_rng
                self._manual_vag_wants_rng = wants

    # ------------------------------------------------------------------
    # model apply plumbing
    # ------------------------------------------------------------------

    def _apply(self, params, extra_state, training: bool, rng_key, args, kwargs):
        """Pure forward: returns (outputs, new_extra_state)."""
        if self.model.is_flax:
            # training means dropout: a config with dropout_rate > 0 trains
            # non-deterministic by default (torch .train() parity) — the same
            # semantics the manual 1f1b path has, so flipping
            # pipeline_schedule never toggles regularization. An explicit
            # deterministic= in the call always wins.
            if (
                training
                and rng_key is not None
                and self._train_dropout_default
                and "deterministic" not in kwargs
                and len(args) <= self._det_argpos  # not already positional
            ):
                kwargs = {**kwargs, "deterministic": False}
            variables = {"params": params, **extra_state}
            mutable = list(extra_state.keys()) if (training and extra_state) else False
            rngs = {"dropout": rng_key} if (training and rng_key is not None) else None
            out = self.model.definition.apply(
                variables, *args, rngs=rngs, mutable=mutable, **kwargs
            )
            if mutable:
                outputs, new_state = out
                return outputs, new_state
            return out, extra_state
        else:
            return self.model.definition(params, *args, **kwargs), extra_state

    def _cast_params(self, params):
        if self.sharding_config.offload_params_to_host:
            from .parallel.sharding import device_memory_space, transfer_tree

            params = transfer_tree(params, device_memory_space())
        c = self.precision.compute_dtype
        return jax.tree_util.tree_map(
            lambda p: p.astype(c) if jnp.issubdtype(p.dtype, jnp.floating) else p, params
        )

    def _warn_pipeline_fallback(self, args, kwargs, reason: str = None):
        """One-time notice that a 1F1B-capable model is training through the
        AD/GPipe fallback: gradients are equivalent, but the O(M) activation
        stash silently replaces the configured O(S) schedule's memory
        profile — a model sized for 1F1B can OOM the moment a batch key
        forces this path (ADVICE r5). Names the offending key(s)."""
        if self._pipeline_fallback_warned:
            return
        self._pipeline_fallback_warned = True
        if reason is None:
            named = {}
            extra_positional = 0
            for i, a in enumerate(args):
                if i < len(self._call_argnames):
                    named[self._call_argnames[i]] = a
                else:
                    extra_positional += 1
            named.update(kwargs)
            offending = sorted(k for k in named if k not in ("input_ids", "labels"))
            if extra_positional:
                offending.append(f"{extra_positional} extra positional arg(s)")
            if offending:
                reason = f"batch key(s) {', '.join(offending)} forced the fallback"
            elif "labels" not in named:
                reason = "the batch carries no labels"
            else:
                reason = "the batch does not match the (input_ids, labels) signature"
        logger.warning(
            "model exposes pipeline_value_and_grad (1f1b schedule) but this "
            "training step runs through the AD/GPipe fallback: %s. The "
            "fallback computes identical gradients but stashes activations "
            "for ALL microbatches (O(M) memory instead of the schedule's "
            "O(S)) — a model sized for 1F1B can OOM here. Feed plain "
            "(input_ids, labels) batches to use the configured schedule.",
            reason,
        )

    # ------------------------------------------------------------------
    # staged computations
    # ------------------------------------------------------------------

    def _fwd_bwd_fn(self, params, extra_state, scale, rng_key, args, kwargs):
        """outputs + grads in one computation (see module docstring)."""
        if self._manual_vag is not None and not extra_state:
            ids, labels = _extract_lm_batch(args, kwargs, self._call_argnames)
            if labels is not None:
                # scale seeds the manual backward (scaled-domain grads, same
                # underflow protection as the AD path below), then unscale
                # before the finite check. scale=/rng= are passed only when
                # needed: the hook is duck-typed, and a 3-arg implementation
                # keeps working without fp16/dropout.
                extra = {}
                if scale is not None:
                    extra["scale"] = scale
                if self._manual_vag_wants_rng and rng_key is not None:
                    extra["rng"] = rng_key
                out, grads = self._manual_vag(
                    self._cast_params(params), ids, labels, **extra
                )
                # hooks return a scalar loss, or an outputs dict with "loss"
                # (MoE surfaces {"loss","lm_loss","aux_loss"} — same contract
                # as the AD path's model outputs)
                outputs = (
                    {k: v.astype(jnp.float32) for k, v in out.items()}
                    if isinstance(out, dict)
                    else {"loss": out.astype(jnp.float32)}
                )
                loss = outputs["loss"]
                if scale is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g: (g.astype(jnp.float32) / scale), grads
                    )
                    finite = jnp.all(
                        jnp.asarray(
                            [jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)]
                        )
                    )
                else:
                    grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
                    finite = jnp.asarray(True)
                return outputs, extra_state, grads, finite, loss

        if self._manual_vag is not None:
            self._warn_pipeline_fallback(
                args, kwargs,
                reason="live mutable collections cannot thread through the "
                       "manual backward" if extra_state else None,
            )

        def loss_of(p):
            outputs, new_state = self._apply(
                self._cast_params(p), extra_state, True, rng_key, args, kwargs
            )
            loss = self.loss_fn(outputs)
            loss = loss.astype(jnp.float32)
            scaled = loss * scale if scale is not None else loss
            return scaled, (outputs, new_state, loss)

        grads, (outputs, new_state, loss) = jax.grad(loss_of, has_aux=True)(params)
        if scale is not None:
            grads = jax.tree_util.tree_map(lambda g: (g / scale).astype(jnp.float32), grads)
            finite = jnp.all(
                jnp.asarray(
                    [jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)]
                )
            )
        else:
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
            finite = jnp.asarray(True)
        outputs = _cast_float_outputs(outputs, self.precision.output_dtype)
        return outputs, new_state, grads, finite, loss

    def _get_jit(self, name: str, fn, **jit_kwargs):
        if name not in self._jit_cache:
            self._jit_cache[name] = jax.jit(fn, **jit_kwargs)
        return self._jit_cache[name]

    def model_call(self, training: bool, *args, **kwargs):
        # bool/str/None call-args (flax `deterministic`, BatchNorm `train`
        # flags) feed Python control flow in the module, so they enter the
        # jit as statics, not tracers.
        t_args, s_args, t_kw, s_kw = _split_static_call(args, kwargs)
        if not training:
            fwd = self._get_jit(
                "eval_fwd",
                lambda p, es, a, kw, sa, skw: _cast_float_outputs(
                    self._apply(
                        self._cast_params(p), es, False, None, *_merge_static_call(a, kw, sa, skw)
                    )[0],
                    self.precision.output_dtype,
                ),
                static_argnums=(4, 5),
            )
            return fwd(self.params, self.extra_state, t_args, t_kw, s_args, s_kw)

        rng_key = default_keychain().next_key("dropout")
        scale = self.scale_state["scale"] if self.scale_state is not None else None
        if self.telemetry is not None:
            self.telemetry.note_batch(args, kwargs, self._call_argnames)
            from .telemetry import forensics as _forensics

            _forensics.note_call(
                "train_fwd_bwd",
                {"args": t_args, "kwargs": t_kw, "statics": (s_args, s_kw)},
            )

        fwd_bwd = self._get_jit(
            "fwd_bwd",
            lambda p, es, s, k, a, kw, sa, skw: self._fwd_bwd_fn(
                p, es, s, k, *_merge_static_call(a, kw, sa, skw)
            ),
            static_argnums=(6, 7),
        )
        outputs, new_state, grads, finite, loss = fwd_bwd(
            self.params, self.extra_state, scale, rng_key, t_args, t_kw, s_args, s_kw
        )
        self.extra_state = new_state
        self._pending_grads = (grads, finite)
        self._pending_loss = loss
        return outputs

    def backward(self, loss=None):
        """Fold pending grads into the accumulation buffer."""
        if self._pending_grads is None:
            raise RuntimeError(
                "accelerator.backward() called but no forward pass is pending. "
                "Call the prepared model first (in train mode)."
            )
        grads, finite = self._pending_grads
        self._pending_grads = None
        # inv_steps is a traced argument (not a closure constant) so changing
        # accelerator.gradient_accumulation_steps mid-run takes effect.
        inv_steps = jnp.asarray(1.0 / self.gradient_state.num_steps, jnp.float32)
        if self._accum_grads is None:
            scale_fn = self._get_jit(
                "accum_init", lambda g, inv: jax.tree_util.tree_map(lambda x: x * inv, g)
            )
            self._accum_grads = scale_fn(grads, inv_steps)
            self._accum_finite = finite
        else:
            add_fn = self._get_jit(
                "accum_add",
                lambda acc, g, inv, f_acc, f: (
                    jax.tree_util.tree_map(lambda a, x: a + x * inv, acc, g),
                    jnp.logical_and(f_acc, f),
                ),
                donate_argnums=(0,),
            )
            self._accum_grads, self._accum_finite = add_fn(
                self._accum_grads, grads, inv_steps, self._accum_finite, finite
            )

    # ------------------------------------------------------------------
    # optimizer wiring
    # ------------------------------------------------------------------

    def attach_optimizer(self, optimizer: optax.GradientTransformation, schedule=None):
        from .parallel.sharding import (
            device_memory_space,
            infer_opt_state_sharding,
            transfer_tree,
            tree_with_memory_kind,
        )

        self.optimizer = optimizer
        self.schedule = schedule
        # opt shardings derive from the DEVICE view of the param shardings:
        # memory kinds in a jit's out_shardings must be uniform per memory
        # space or the SPMD partitioner rejects the rank-0 annotations
        base_param_sharding = (
            tree_with_memory_kind(self.param_sharding, "device")
            if self.sharding_config.offload_params_to_host
            else self.param_sharding
        )
        self.opt_state_sharding = infer_opt_state_sharding(
            optimizer, self.params, base_param_sharding, self.mesh
        )
        device_space = device_memory_space()
        init = self._get_jit(
            "opt_init",
            lambda p: optimizer.init(transfer_tree(p, device_space)),
            out_shardings=self.opt_state_sharding,
        )
        self.opt_state = init(self.params)
        if self.sharding_config.offload_optimizer_state:
            # ZeRO-offload analog: Adam moments (2x params in fp32 — usually
            # the single biggest HBM line item) live in pinned host between
            # steps; _update_fn streams them to HBM per update and the step
            # wrappers re-place them host-side after. Scalar leaves (step
            # counts) stay on device — the SPMD partitioner rejects
            # placement annotations on rank-0 buffers.
            from .parallel.sharding import with_memory_kind

            self.opt_state_sharding = jax.tree_util.tree_map(
                lambda sh, leaf: with_memory_kind(sh, "pinned_host") if getattr(leaf, "ndim", 0) >= 1 else sh,
                self.opt_state_sharding,
                self.opt_state,
            )
            self.opt_state = self._replace_offloaded_opt(self.opt_state)

    def _replace_offloaded_opt(self, opt_state):
        return jax.tree_util.tree_map(
            lambda x, sh: jax.device_put(x, sh) if getattr(x, "ndim", 0) >= 1 else x,
            opt_state,
            self.opt_state_sharding,
        )

    def _replace_offloaded_params(self, params):
        return jax.tree_util.tree_map(
            lambda x, sh: jax.device_put(x, sh) if getattr(x, "ndim", 0) >= 1 else x,
            params,
            self.param_sharding,
        )

    def _update_fn(self, params, opt_state, grads, scale_state, finite, max_norm):
        """One optimizer update: clip -> optax -> apply; fp16 skip via cond.
        Host-offloaded state streams HBM-ward here and back at the end."""
        from .parallel.sharding import device_memory_space, transfer_tree

        offload_opt = self.sharding_config.offload_optimizer_state
        offload_p = self.sharding_config.offload_params_to_host
        if offload_opt:
            opt_state = transfer_tree(opt_state, device_memory_space())
        if offload_p:
            params = transfer_tree(params, device_memory_space())
        if max_norm is not None:
            gnorm = optax.global_norm(grads)
            clip_scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * clip_scale, grads)

        def do_update(operand):
            params, opt_state, grads = operand
            updates, new_opt = self.optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt

        if scale_state is None:
            new_params, new_opt = do_update((params, opt_state, grads))
            return new_params, new_opt, None, jnp.asarray(False)

        def skip(operand):
            params, opt_state, grads = operand
            return params, opt_state

        new_params, new_opt = jax.lax.cond(
            finite, do_update, skip, (params, opt_state, grads)
        )
        new_scale = self._scale_state_update(scale_state, finite)
        return new_params, new_opt, new_scale, jnp.logical_not(finite)

    def _scale_state_update(self, scale_state, finite):
        """GradScaler growth/backoff (shared by the GSPMD update and the
        compressed shard_map step): grow after growth_interval consecutive
        finite steps, back off (floored at 1.0) on overflow."""
        gk = self.precision.grad_scaler
        return jax.lax.cond(
            finite,
            lambda s: {
                "scale": jnp.where(
                    s["growth_tracker"] + 1 >= gk.growth_interval,
                    s["scale"] * gk.growth_factor,
                    s["scale"],
                ),
                "growth_tracker": jnp.where(
                    s["growth_tracker"] + 1 >= gk.growth_interval,
                    0,
                    s["growth_tracker"] + 1,
                ),
            },
            lambda s: {
                "scale": jnp.maximum(s["scale"] * gk.backoff_factor, 1.0),
                "growth_tracker": jnp.zeros((), jnp.int32),
            },
            scale_state,
        )

    def optimizer_step(self):
        if self.optimizer is None:
            raise RuntimeError("optimizer not attached; prepare(model, optimizer) together")
        if self._accum_grads is None:
            logger.warning("optimizer.step() called with no accumulated gradients; skipping")
            return
        max_norm = self._clip_max_norm
        use_clip = max_norm is not None
        key = "update_clip" if use_clip else "update"
        if key not in self._jit_cache:
            if use_clip:
                fn = lambda p, o, g, s, f, mn: self._update_fn(p, o, g, s, f, mn)
            else:
                fn = lambda p, o, g, s, f: self._update_fn(p, o, g, s, f, None)
            self._jit_cache[key] = jax.jit(
                fn, donate_argnums=(0, 1, 2) if self.donate_state else (2,)
            )
        finite = self._accum_finite if self._accum_finite is not None else jnp.asarray(True)
        call_args = [self.params, self.opt_state, self._accum_grads, self.scale_state, finite]
        if use_clip:
            call_args.append(jnp.asarray(max_norm, jnp.float32))
        new_params, new_opt, new_scale, skipped = self._jit_cache[key](*call_args)
        if self.sharding_config.offload_params_to_host:
            new_params = self._replace_offloaded_params(new_params)
        if self.sharding_config.offload_optimizer_state:
            new_opt = self._replace_offloaded_opt(new_opt)
        self.params = new_params
        self.opt_state = new_opt
        if self.scale_state is not None:
            self.scale_state = new_scale
            self._last_skipped = skipped
        else:
            self._last_skipped = False
        self._accum_grads = None
        self._accum_finite = None
        self.extra_state = _roll_fp8_stats(self.extra_state)
        self.step_count += 1
        if self.telemetry is not None:
            self.telemetry.on_optimizer_step(self)

    def last_step_skipped(self) -> bool:
        if isinstance(self._last_skipped, bool):
            return self._last_skipped
        return bool(jax.device_get(self._last_skipped))

    def zero_grad(self):
        self._accum_grads = None
        self._accum_finite = None

    def clip_grad_norm(self, max_norm: float):
        """Record the clip threshold for the coming update and return the
        current global grad norm (reference clip_grad_norm_ returns it).

        Before any backward there are no accumulated grads and the returned
        norm is 0.0 — the same value torch.nn.utils.clip_grad_norm_ returns
        when no parameter has a .grad; the threshold still applies to the
        next update."""
        self._clip_max_norm = float(max_norm)
        if self._accum_grads is None:
            return jnp.asarray(0.0)
        norm_fn = self._get_jit("grad_norm", optax.global_norm)
        return norm_fn(self._accum_grads)

    def current_learning_rate(self):
        if self.schedule is not None:
            return float(self.schedule(self.step_count))
        # try to find a scalar lr hyperparam in the opt state
        try:
            hp = getattr(self.opt_state, "hyperparams", None)
            if hp and "learning_rate" in hp:
                return float(jax.device_get(hp["learning_rate"]))
        except Exception:
            pass
        return None

    def current_variables(self):
        if self.model.is_flax:
            return {"params": self.params, **self.extra_state}
        return self.params

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------

    def state_dict(self) -> dict:
        out = {
            "params": self.params,
            "opt_state": self.opt_state,
            "step_count": self.step_count,
        }
        if self.extra_state:
            out["extra_state"] = self.extra_state
        if self.scale_state is not None:
            out["scale"] = dict(self.scale_state)
        return out

    @staticmethod
    def _own_restored_buffers(tree):
        """Re-materialize restored leaves as executable outputs.

        The step/update programs donate params and opt_state. A donated
        buffer must be exclusively owned by its array; ``device_put``
        results restored from a checkpoint do not always satisfy that
        (scalar leaves can come out of jax's shared constant pool), and an
        executable deserialized from the persistent compilation cache will
        honor the donation where a freshly compiled CPU executable refuses
        it — the runtime then reuses the donated storage for an unrelated
        allocation while the aliased output still reads it (observed: adam
        ``mu`` clobbered to the backward seed 1.0 one step after
        ``load_state``). Copying through a compiled program yields
        uniquely-owned buffers that are safe to donate.
        """
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        idx = [i for i, leaf in enumerate(leaves) if isinstance(leaf, jax.Array)]
        if idx:
            picked = [leaves[i] for i in idx]
            copier = jax.jit(
                lambda xs: [jnp.copy(x) for x in xs],
                out_shardings=[x.sharding for x in picked],
            )
            for i, fresh in zip(idx, copier(picked)):
                leaves[i] = fresh
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def load_state_dict(self, state: dict):
        self.params = self._own_restored_buffers(jax.tree_util.tree_map(
            lambda like, v: jax.device_put(jnp.asarray(v, like.dtype), like.sharding),
            self.params, state["params"],
        ))
        if self.opt_state is not None and state.get("opt_state") is not None:
            self.opt_state = self._own_restored_buffers(jax.tree_util.tree_map(
                lambda like, v: jax.device_put(jnp.asarray(v, like.dtype), like.sharding)
                if isinstance(like, jax.Array)
                else v,
                self.opt_state, state["opt_state"],
            ))
        self.step_count = int(state.get("step_count", 0))
        if "extra_state" in state:
            self.extra_state = replicate(state["extra_state"], self.mesh)
        if "scale" in state and self.scale_state is not None:
            self.scale_state = {
                "scale": jnp.asarray(state["scale"]["scale"], jnp.float32),
                "growth_tracker": jnp.asarray(state["scale"]["growth_tracker"], jnp.int32),
            }

    def load_optimizer_state(self, state: dict):
        if state.get("opt_state") is not None and self.opt_state is not None:
            self.opt_state = self._own_restored_buffers(jax.tree_util.tree_map(
                lambda like, v: jax.device_put(jnp.asarray(v, like.dtype), like.sharding)
                if isinstance(like, jax.Array)
                else v,
                self.opt_state, state["opt_state"],
            ))
        if "step_count" in state:
            self.step_count = int(state["step_count"])

    # ------------------------------------------------------------------
    # fully-fused train step (the perf path)
    # ------------------------------------------------------------------

    def build_train_step(
        self,
        loss_fn: Optional[Callable] = None,
        micro_steps: Optional[int] = None,
        steps_per_call: Optional[int] = None,
    ):
        """One jit: split batch into micro-batches, lax.scan fwd+bwd
        accumulating grads, clip, update. Returns step(batch)->metrics.

        ``steps_per_call=K`` fuses K FULL optimizer steps (each with its own
        batch and RNG stream) into ONE executable via lax.scan — the
        MaxText-style train loop. The returned runner then takes a batch
        whose leaves carry a leading [K, ...] axis (K stacked per-step
        batches) and returns the LAST step's metrics plus ``loss_mean`` over
        the K steps. This amortizes per-dispatch host latency, which
        dominates for sub-50ms steps on remote-attached runtimes."""
        micro = micro_steps or self.gradient_state.num_steps
        if (
            (
                getattr(self.sharding_config, "grad_compression_dtype", None)
                or getattr(self.sharding_config, "grad_compression_rank", None)
            )
            and self.mesh is not None
            and self.mesh.shape.get("replica", 1) > 1
        ):
            if steps_per_call and steps_per_call > 1:
                raise NotImplementedError(
                    "steps_per_call>1 is not supported together with gradient "
                    "compression (the compressed step runs under shard_map)"
                )
            return self._build_compressed_replica_step(loss_fn, micro)
        user_loss = loss_fn
        max_norm = self._clip_max_norm

        def loss_and_state(params, extra_state, rng_key, batch):
            """-> (loss, new_extra_state). user_loss path can't update
            mutable collections (no handle to return them) — documented."""
            if user_loss is not None:
                return (
                    user_loss(self._make_apply(extra_state, rng_key), params, batch),
                    extra_state,
                )
            args, kwargs = _batch_to_call(batch)
            outputs, new_state = self._apply(
                self._cast_params(params), extra_state, True, rng_key, args, kwargs
            )
            return self.loss_fn(outputs).astype(jnp.float32), new_state

        manual_vag = self._manual_vag if user_loss is None else None

        def step_fn(params, opt_state, extra_state, scale_state, rng_key, batch):
            scale = scale_state["scale"] if scale_state is not None else None

            def one_micro(carry, mb):
                acc, loss_acc, key, es = carry
                key, sub = jax.random.split(key)

                args, kwargs = _batch_to_call(mb)
                ids, labels = _extract_lm_batch(args, kwargs, self._call_argnames)
                if manual_vag is not None and (es or labels is None):
                    # trace-time notice (the routing is static per compile)
                    self._warn_pipeline_fallback(
                        args, kwargs,
                        reason="live mutable collections cannot thread "
                               "through the manual backward" if es else None,
                    )
                if manual_vag is not None and not es and labels is not None:
                    # model-owned backward schedule (1f1b pipeline): the loss
                    # scale seeds the manual backward's cotangent, so the
                    # whole backward runs scaled (fp16 underflow protection,
                    # same as AD) and grads arrive scaled for the post-scan
                    # /scale + finite check. scale=/rng= only when needed
                    # (duck-typed hook: 3-arg implementations stay valid).
                    extra = {}
                    if scale is not None:
                        extra["scale"] = scale
                    if self._manual_vag_wants_rng:
                        extra["rng"] = sub
                    out, g = manual_vag(self._cast_params(params), ids, labels, **extra)
                    # dict-returning hooks (MoE) -> the scalar for the scan
                    l = (out["loss"] if isinstance(out, dict) else out).astype(
                        jnp.float32
                    )
                    new_es = es
                else:

                    def scaled_loss(p):
                        l, new_es = loss_and_state(p, es, sub, mb)
                        return (l * scale if scale is not None else l), (l, new_es)

                    g, (l, new_es) = jax.grad(scaled_loss, has_aux=True)(params)
                acc = jax.tree_util.tree_map(
                    lambda a, x: a + x.astype(jnp.float32) / micro, acc, g
                )
                return (acc, loss_acc + l / micro, key, new_es), None

            zero = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            carry0 = (zero, jnp.asarray(0.0), rng_key, extra_state)
            if micro > 1:
                mbs = jax.tree_util.tree_map(
                    lambda x: x.reshape((micro, x.shape[0] // micro) + x.shape[1:]), batch
                )
                (grads, loss, _, new_extra), _ = jax.lax.scan(one_micro, carry0, mbs)
            else:
                (grads, loss, _, new_extra), _ = one_micro(carry0, batch)
            if scale is not None:
                grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
                finite = jnp.all(
                    jnp.asarray([jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)])
                )
            else:
                finite = jnp.asarray(True)
            new_params, new_opt, new_scale, skipped = self._update_fn(
                params, opt_state, grads, scale_state, finite,
                jnp.asarray(max_norm, jnp.float32) if max_norm is not None else None,
            )
            if user_loss is None:
                # the user-loss path cannot record amaxes (no handle to
                # return mutated collections) — rolling there would drain
                # the history; see _roll_fp8_stats
                new_extra = _roll_fp8_stats(new_extra)
            metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
            return new_params, new_opt, new_extra, new_scale, skipped, metrics

        if steps_per_call and steps_per_call > 1:

            def multi_fn(params, opt_state, extra_state, scale_state, rng_key, batches):
                def body(carry, mb):
                    p, o, es, ss, key = carry
                    key, sub = jax.random.split(key)
                    p, o, es, ss, skipped, metrics = step_fn(p, o, es, ss, sub, mb)
                    return (p, o, es, ss, key), (metrics, skipped)

                (p, o, es, ss, _), (ms, sk) = jax.lax.scan(
                    body, (params, opt_state, extra_state, scale_state, rng_key), batches
                )
                metrics = jax.tree_util.tree_map(lambda x: x[-1], ms)
                metrics["loss_mean"] = jnp.mean(ms["loss"])
                # ANY skipped step inside the fused window must surface
                # through optimizer_step_was_skipped, not just the last one
                skipped_any = jnp.any(jnp.asarray(sk)) if sk is not None else sk
                return p, o, es, ss, skipped_any, metrics

            fused_fn = multi_fn
        else:
            fused_fn = step_fn
        donate = (0, 1) if self.donate_state else ()
        jitted = jax.jit(fused_fn, donate_argnums=donate)
        if self.telemetry is not None:
            from .telemetry import forensics as _forensics

            _forensics.register(
                "train_step", donate=donate,
                statics={"micro_steps": micro, "steps_per_call": steps_per_call},
            )
        cost_captured = []

        def run(batch):
            tm = self.telemetry
            t0 = time.perf_counter() if tm is not None else None
            rng_key = default_keychain().next_key("train_step")
            if tm is not None:
                from .telemetry import forensics as _forensics

                # fingerprint BEFORE dispatch: a changed batch signature
                # here is the recompile this very call is about to pay
                _forensics.note_call("train_step", {"batch": batch})
            new_params, new_opt, new_extra, new_scale, skipped, metrics = jitted(
                self.params, self.opt_state, self.extra_state, self.scale_state, rng_key, batch
            )
            if self.sharding_config.offload_params_to_host:
                new_params = self._replace_offloaded_params(new_params)
            if self.sharding_config.offload_optimizer_state:
                new_opt = self._replace_offloaded_opt(new_opt)
            self.params, self.opt_state = new_params, new_opt
            self.extra_state = new_extra
            if self.scale_state is not None:
                self.scale_state = new_scale
                self._last_skipped = skipped
            self.step_count += steps_per_call if steps_per_call else 1
            if tm is not None:
                from .telemetry.metrics import batch_token_count

                tokens, samples, seq_len = batch_token_count(batch)
                tm.on_step(
                    self, time.perf_counter() - t0, tokens=tokens,
                    samples=samples, seq_len=seq_len,
                    steps=steps_per_call if steps_per_call else 1,
                    metrics=metrics, exe="train_step",
                )
                if tm.costs is not None and not cost_captured:
                    # once, on the (warmup) first step: re-lower against
                    # the live avals (one trace, no backend compile — the
                    # compiled-form memory analysis is added only when the
                    # persistent cache can serve it) so the roofline row
                    # exists from step 1
                    cost_captured.append(True)
                    try:
                        tm.costs.capture_lowered("train_step", jitted.lower(
                            self.params, self.opt_state, self.extra_state,
                            self.scale_state, rng_key, batch,
                        ))
                    except Exception:
                        pass
            return metrics

        # expose the underlying jitted executable to the static program
        # auditor (`accelerate-tpu audit`): the runner closure hides it,
        # and the auditor needs the fn + effective donation set to trace
        run._audit_fn = jitted
        run._audit_donate = donate
        return run

    def audit_entrypoints(self, step, batch) -> list:
        """Entry-point specs for ``accelerate_tpu.analysis.program_audit``
        covering the fused train step ``build_train_step`` returned:
        the underlying jitted fn, the live optimizer/param state as
        example args, and the effective ``donate_argnums``. Trace-only —
        nothing executes. ``batch`` is one example batch shaped like the
        real traffic (what the signature forensics fingerprint too)."""
        import jax as _jax

        fn = getattr(step, "_audit_fn", None)
        if fn is None:
            return []
        donate = tuple(getattr(step, "_audit_donate", ()) or ())
        return [dict(
            name="train_step", fn=fn,
            args=(self.params, self.opt_state, self.extra_state,
                  self.scale_state, _jax.random.PRNGKey(0), batch),
            donate=donate, donate_expected=bool(donate),
            compute_dtype=("bfloat16"
                           if self.state.mixed_precision == "bf16" else None),
        )]

    def _make_apply(self, extra_state, rng_key):
        def apply_fn(params, *args, **kwargs):
            out, _ = self._apply(self._cast_params(params), extra_state, True, rng_key, args, kwargs)
            return out

        return apply_fn

    def _build_compressed_replica_step(self, loss_fn, micro):
        """Train step with a COMPRESSED cross-slice gradient all-reduce — the
        TPU analog of the reference's DDP comm hooks (fp16/bf16/powerSGD on
        the gradient bucket all-reduce, reference utils/dataclasses.py:
        111-208). The step runs under an explicit shard_map over the mesh so
        the reduction hops are separate collectives:

          1. fp32 reduction over the intra-slice axes — rides ICI, cheap.
             With ``fsdp > 1`` the param shards enter sharded, are
             all-gathered before the forward, and AD's transpose of that
             gather IS the ZeRO reduce-scatter — grads leave fsdp-sharded.
          2. the "replica" hop — DCN-crossing on a multi-slice HYBRID mesh —
             carries either ``grad_compression_dtype`` words (bf16/fp16
             halve, int8 quarters the bytes) or, with
             ``grad_compression_rank``, PowerSGD low-rank factors
             ((m+n)*rank floats instead of m*n, warm-started Q, per-replica
             error feedback).

        int8 uses a cross-replica-consistent per-tensor scale with headroom
        so the on-wire psum cannot overflow (max |q| <= 127/num_replicas).
        fp16 loss scaling composes: the backward runs scaled, grads unscale
        before compression, and the finite check gates the update exactly
        like the GSPMD path."""
        from jax.sharding import PartitionSpec as P

        from .parallel.sharding import shard_map_compat as shard_map
        from .utils.serialization import flatten_pytree, unflatten_to_like

        mesh = self.mesh
        comp_name = self.sharding_config.grad_compression_dtype
        rank = self.sharding_config.grad_compression_rank
        optimizer = self.optimizer
        user_loss = loss_fn
        n_replica = mesh.shape["replica"]
        fsdp_size = mesh.shape.get("fsdp", 1)
        data_axes = tuple(a for a in ("data",) if mesh.shape.get(a, 1) > 1)
        batch_axes = ("replica",) + data_axes + (("fsdp",) if fsdp_size > 1 else ())

        param_specs = jax.tree_util.tree_map(
            lambda s: s.spec, self.param_sharding
        )
        opt_specs = jax.tree_util.tree_map(
            lambda s: s.spec, self.opt_state_sharding
        )

        def _fsdp_dim(spec):
            for i, part in enumerate(spec):
                names = (part,) if isinstance(part, str) else tuple(part or ())
                if "fsdp" in names:
                    return i
            return None

        def _gather_full(p, spec):
            d = _fsdp_dim(spec)
            if d is None or fsdp_size == 1:
                return p
            return jax.lax.all_gather(p, "fsdp", axis=d, tiled=True)

        if rank:
            comp_state = self._init_powersgd_state(rank)
        else:
            comp_state = {}
        comp_paths = set(comp_state)

        def _dtype_hop(g):
            """The plain compressed replica-mean for one fp32 grad leaf."""
            if comp_name == "int8":
                cap = 127 // n_replica  # sum over R replicas stays <= 127
                absmax = jax.lax.pmax(jnp.max(jnp.abs(g)), "replica")
                scale = absmax / cap + 1e-30
                q = jnp.clip(jnp.round(g / scale), -cap, cap).astype(jnp.int8)
                summed = jax.lax.psum(q, "replica")  # int8 on the wire
                return summed.astype(jnp.float32) * scale / n_replica
            if comp_name is None:
                return jax.lax.pmean(g, "replica")
            comp = jnp.dtype(comp_name)
            return jax.lax.pmean(g.astype(comp), "replica").astype(jnp.float32)

        def _powersgd_hop(g, state):
            """PowerSGD rank-r replica mean with error feedback (reference
            powerSGD_hook): M = g + error; P = MQ -> pmean -> orthonormalize;
            Q' = M^T P -> pmean; ghat = P Q'^T; error' = M - ghat. Leaves
            with >2 dims run per-slice along dim 0 (layer-scanned stacks).
            State leaves carry a leading replica dim (sliced to 1 inside the
            shard_map): the error buffer GENUINELY differs per replica —
            declaring it replicated would be an SPMD lie that any reshard
            could collapse."""
            q, err = state["q"][0], state["err"][0]

            def one(m2d, q2d):
                p = jax.lax.pmean(m2d @ q2d, "replica")
                p, _ = jnp.linalg.qr(p)
                q_new = jax.lax.pmean(m2d.T @ p, "replica")
                return p @ q_new.T, q_new

            m = (g + err).astype(jnp.float32)
            if g.ndim == 2:
                ghat, q_new = one(m, q)
            else:
                flat = m.reshape(m.shape[0], m.shape[1], -1)
                ghat, q_new = jax.vmap(one)(flat, q)
                ghat = ghat.reshape(g.shape)
            return ghat, {"q": q_new[None], "err": (m.reshape(g.shape) - ghat)[None]}

        def body(params, opt_state, extra_state, scale_state, comp_state, rng_key, batch):
            scale = scale_state["scale"] if scale_state is not None else None
            idx = jax.lax.axis_index(batch_axes)
            base_key = jax.random.fold_in(rng_key, idx)

            def one_micro(carry, mb):
                acc, loss_acc, key, es = carry
                key, sub = jax.random.split(key)

                def local_loss(p_shards):
                    p = jax.tree_util.tree_map(_gather_full, p_shards, param_specs)
                    # same loss_fn contract as the normal path: a user-
                    # supplied fn receives (apply_fn, params, batch)
                    if user_loss is not None:
                        l = user_loss(self._make_apply(es, sub), p, mb).astype(jnp.float32)
                        new_es = es
                    else:
                        args, kwargs = _batch_to_call(mb)
                        outputs, new_es = self._apply(
                            self._cast_params(p), es, True, sub, args, kwargs
                        )
                        l = self.loss_fn(outputs).astype(jnp.float32)
                    return (l * scale if scale is not None else l), (l, new_es)

                g, (l, new_es) = jax.grad(local_loss, has_aux=True)(params)
                acc = jax.tree_util.tree_map(
                    lambda a, x: a + x.astype(jnp.float32) / micro, acc, g
                )
                return (acc, loss_acc + l / micro, key, new_es), None

            zero = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            carry0 = (zero, jnp.asarray(0.0), base_key, extra_state)
            if micro > 1:
                mbs = jax.tree_util.tree_map(
                    lambda x: x.reshape((micro, x.shape[0] // micro) + x.shape[1:]), batch
                )
                (grads, loss, _, new_es), _ = jax.lax.scan(one_micro, carry0, mbs)
            else:
                (grads, loss, _, new_es), _ = one_micro(carry0, batch)

            # unscale + finite check BEFORE the lossy compression (a saturated
            # fp16 grad must trigger the skip, not silently clip)
            if scale is not None:
                grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
            finite = jnp.all(
                jnp.asarray([jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)])
            )
            finite = jax.lax.pmin(finite.astype(jnp.int32), batch_axes).astype(bool)

            # intra-slice (ICI) fp32 reduction, PER LEAF by its sharding:
            # - fsdp-sharded leaves: the fsdp sum already happened in AD
            #   (all_gather transpose = psum_scatter) — normalize to a mean;
            # - replicated leaves (norms, leaves under the size threshold):
            #   AD inserted NO fsdp collective, each member only saw its own
            #   sub-batch — pmean over fsdp alongside data.
            def _ici_mean(g, spec):
                sharded = _fsdp_dim(spec) is not None and fsdp_size > 1
                axes = data_axes + (
                    ("fsdp",) if (fsdp_size > 1 and not sharded) else ()
                )
                if axes:
                    g = jax.lax.pmean(g, axes)
                return g / fsdp_size if sharded else g

            grads = jax.tree_util.tree_map(_ici_mean, grads, param_specs)

            # the replica (DCN) hop, compressed
            flat_g = flatten_pytree(grads)
            new_comp = {}
            for path in flat_g:
                if path in comp_paths:
                    flat_g[path], new_comp[path] = _powersgd_hop(
                        flat_g[path], comp_state[path]
                    )
                else:
                    flat_g[path] = _dtype_hop(flat_g[path])
            grads = unflatten_to_like(flat_g, grads)

            loss = jax.lax.pmean(loss, batch_axes)
            # mutable collections (e.g. BatchNorm stats) were updated from
            # each shard's local batch: average float leaves so every shard
            # leaves with the same, global-batch-equivalent statistics
            new_es = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, batch_axes)
                if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                new_es,
            )
            # pre-clip norm, global across fsdp shards (each member must
            # apply the SAME clip factor or shards drift apart). Only the
            # fsdp-SHARDED leaves psum over fsdp — replicated leaves would
            # double-count.
            flat_for_norm = flatten_pytree(grads)
            flat_specs = flatten_pytree(param_specs)
            sq_sharded = sum(
                jnp.sum(jnp.square(g)) for p, g in flat_for_norm.items()
                if _fsdp_dim(flat_specs[p]) is not None
            ) if fsdp_size > 1 else 0.0
            sq_rep = sum(
                jnp.sum(jnp.square(g)) for p, g in flat_for_norm.items()
                if fsdp_size == 1 or _fsdp_dim(flat_specs[p]) is None
            )
            if fsdp_size > 1:
                sq_sharded = jax.lax.psum(sq_sharded, "fsdp")
            grad_norm = jnp.sqrt(sq_rep + sq_sharded)
            max_norm = self._clip_max_norm
            if max_norm is not None:
                factor = jnp.minimum(1.0, max_norm / (grad_norm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * factor, grads)

            def do_update(operand):
                params, opt_state, grads = operand
                updates, new_opt = optimizer.update(grads, opt_state, params)
                new_params = jax.tree_util.tree_map(
                    lambda p, u: p + u.astype(p.dtype), params, updates
                )
                return new_params, new_opt

            if scale_state is None:
                new_params, new_opt = do_update((params, opt_state, grads))
                new_scale, skipped = None, jnp.asarray(False)
            else:
                new_params, new_opt = jax.lax.cond(
                    finite, do_update, lambda op: (op[0], op[1]),
                    (params, opt_state, grads),
                )
                new_scale = self._scale_state_update(scale_state, finite)
                skipped = jnp.logical_not(finite)
                if new_comp:
                    # an overflow step's PowerSGD state was computed from
                    # non-finite grads — keep the old state or NaN poisons
                    # every later step (the scaler backoff can't recover it)
                    new_comp = jax.lax.cond(
                        finite, lambda op: op[0], lambda op: op[1],
                        (new_comp, comp_state),
                    )
            metrics = {"loss": loss, "grad_norm": grad_norm}
            return new_params, new_opt, new_es, new_scale, new_comp, skipped, metrics

        rep = P()
        scale_specs = None if self.scale_state is None else jax.tree_util.tree_map(
            lambda _: rep, self.scale_state
        )
        # comp-state leaves carry a leading replica dim (error feedback is
        # per-replica by construction) — shard it honestly
        comp_specs = jax.tree_util.tree_map(lambda _: P("replica"), comp_state)
        stepped = shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, opt_specs, rep, scale_specs, comp_specs, rep, P(batch_axes)),
            out_specs=(param_specs, opt_specs, rep, scale_specs, comp_specs, rep, rep),
            axis_names=set(mesh.axis_names),
            check_vma=False,
        )
        jitted = jax.jit(stepped, donate_argnums=(0, 1, 4) if self.donate_state else ())
        self._comp_state = comp_state

        def run(batch):
            tm = self.telemetry
            t0 = time.perf_counter() if tm is not None else None
            rng_key = default_keychain().next_key("train_step")
            if tm is not None:
                from .telemetry import forensics as _forensics

                _forensics.note_call("train_step", {"batch": batch})
            new_params, new_opt, new_es, new_scale, new_comp, skipped, metrics = jitted(
                self.params, self.opt_state, self.extra_state, self.scale_state,
                self._comp_state, rng_key, batch
            )
            self.params, self.opt_state = new_params, new_opt
            if user_loss is None:
                new_es = _roll_fp8_stats(new_es)
            self.extra_state = new_es
            self._comp_state = new_comp
            if self.scale_state is not None:
                self.scale_state = new_scale
                self._last_skipped = skipped
            self.step_count += 1
            if tm is not None:
                from .telemetry.metrics import batch_token_count

                tokens, samples, seq_len = batch_token_count(batch)
                tm.on_step(
                    self, time.perf_counter() - t0, tokens=tokens,
                    samples=samples, seq_len=seq_len, metrics=metrics,
                    exe="train_step",
                )
            return metrics

        return run

    @staticmethod
    def _powersgd_matrix_view(shape, rank):
        """The ONE owner of PowerSGD's per-leaf eligibility + matrix-view
        rule, shared by the state init and the wire-bytes estimator so they
        can never disagree. Returns ``(m, n, stack, q_shape)`` for an
        eligible leaf, else None. >=3D leaves (layer-scanned stacks) view as
        ``stack`` independent [m, n] matrices along dim 0."""
        if len(shape) < 2:
            return None
        if len(shape) == 2:
            m, n, stack = shape[0], shape[1], 1
            q_shape = (n, rank)
        else:
            m, n, stack = shape[1], int(np.prod(shape[2:])), shape[0]
            q_shape = (shape[0], n, rank)
        if min(m, n) <= 2 * rank:
            return None
        return m, n, stack, q_shape

    @staticmethod
    def replica_wire_bytes(params, grad_compression_dtype=None, grad_compression_rank=None):
        """Bytes each replica puts on the DCN wire per optimizer step under
        the configured gradient compression — the number that makes the
        rank/dtype choice concrete (the reference documents its powerSGD
        hook's tradeoffs qualitatively, utils/dataclasses.py:111-130; this
        quantifies them for YOUR param tree). Mirrors the compressed step's
        per-leaf ROUTING (shared _powersgd_matrix_view): PowerSGD-eligible
        leaves (>=2D, min(m, n) > 2r, stacked leaves per dim-0 slice) send
        the rank-r P and Q factors in fp32; everything else sends the leaf
        at the dtype hop's width (int8 adds one fp32 scale per leaf).

        Byte counts assume the replicated intra-slice layout PowerSGD
        targets (fsdp == 1). On a hybrid fsdp>1 mesh, per-DEVICE traffic
        differs: fsdp-sharded leaves send 1/fsdp shares while replicated
        small leaves are reduced from every mesh position — use the
        Accelerator method, which reports the active config, and treat
        hybrid numbers as the aggregate across the fsdp group. Returns
        {"bytes": int, "compressed_leaves": int, "total_leaves": int}."""
        from .utils.serialization import flatten_pytree

        rank = grad_compression_rank
        comp = grad_compression_dtype
        aliases = {"bf16": "bfloat16", "fp16": "float16", "none": None}
        comp = aliases.get(comp, comp)
        widths = {None: 4, "bfloat16": 2, "float16": 2, "int8": 1}
        if comp not in widths:
            raise ValueError(
                f"grad_compression_dtype {comp!r} not recognized; pick one of "
                "None/'bfloat16'/'float16'/'int8' (aliases bf16/fp16/none)"
            )
        dtype_width = widths[comp]
        total = 0
        n_comp = 0
        n_leaves = 0
        for path, p in flatten_pytree(params).items():
            shape = tuple(getattr(p, "shape", ()))
            size = int(np.prod(shape)) if shape else 1
            n_leaves += 1
            view = TrainEngine._powersgd_matrix_view(shape, rank) if rank else None
            if view is not None:
                m, n, stack, _ = view
                total += stack * (m + n) * rank * 4  # P + Q, fp32
                n_comp += 1
            else:
                total += size * dtype_width + (4 if comp == "int8" else 0)
        return {"bytes": total, "compressed_leaves": n_comp, "total_leaves": n_leaves}

    def _init_powersgd_state(self, rank: int):
        """Warm-start Q + error-feedback buffers for every grad the PowerSGD
        hop will compress: >=2D params whose matrix view is worth rank-r
        (min(m, n) > 2r). 3+D leaves (layer-scanned stacks) compress
        per-dim-0 slice. Keyed by flat path; everything else uses the dtype
        hop. Every leaf gets a leading replica dim — the error buffers are
        genuinely per-replica (sharded P("replica") through the step)."""
        from .utils.serialization import flatten_pytree

        n_replica = self.mesh.shape["replica"]
        state = {}
        key = jax.random.PRNGKey(17)
        for path, p in flatten_pytree(self.params).items():
            shape = tuple(getattr(p, "shape", ()))
            view = self._powersgd_matrix_view(shape, rank)
            if view is None:
                continue
            _, _, _, q_shape = view
            key, sub = jax.random.split(key)
            q = jax.random.normal(sub, q_shape, jnp.float32)
            state[path] = {
                "q": jnp.broadcast_to(q[None], (n_replica,) + q_shape),
                "err": jnp.zeros((n_replica,) + shape, jnp.float32),
            }
        return state


_fp8_mxu_warned = False


def _device_has_fp8_mxu(device) -> bool:
    """fp8 MXU throughput arrives with v6e (Trillium); v5e/v5p and older
    emulate fp8 matmuls via convert-to-bf16 (docs/fp8.md)."""
    import re

    kind = getattr(device, "device_kind", "") or ""
    m = re.search(r"tpu\s*v(\d+)", kind.lower())
    return bool(m) and int(m.group(1)) >= 6


def _warn_fp8_without_mxu_once(device) -> None:
    """One loud notice when mixed_precision='fp8' lands on hardware that
    only emulates fp8: the user just bought overhead, not speed (measured
    ~11pp MFU below bf16 on v5e — BENCH fp8 row), and nothing else at
    runtime says so. The recipe itself stays numerically valid, so this is
    a warning, not an error; the same code path speeds up on v6e+."""
    global _fp8_mxu_warned
    if _fp8_mxu_warned or _device_has_fp8_mxu(device):
        return
    _fp8_mxu_warned = True
    import warnings

    kind = getattr(device, "device_kind", "unknown device")
    warnings.warn(
        f"mixed_precision='fp8' on {kind!r}: this chip has no fp8 MXU, so "
        "XLA emulates fp8 matmuls via convert and training runs SLOWER "
        "than bf16 (see docs/fp8.md, 'When to use it'). The recipe is "
        "numerically faithful and transfers to v6e+/Ironwood unchanged; "
        "use mixed_precision='bf16' here if you want throughput.",
        stacklevel=3,
    )


def _enable_fp8(definition):
    """Flip ``config.use_fp8`` on a model definition that supports the fp8
    recipe (ops/fp8.py); definitions without the knob pass through — their
    matmuls simply stay bf16 (the reference likewise only converts layers
    TE has fp8 kernels for)."""
    import dataclasses as _dc

    cfg = getattr(definition, "config", None)
    if cfg is None or not hasattr(cfg, "use_fp8") or cfg.use_fp8:
        return definition
    try:
        return definition.copy(config=_dc.replace(cfg, use_fp8=True))
    except Exception:  # pragma: no cover - exotic module types
        return definition


def _split_static_call(args, kwargs):
    """Partition call inputs: bool/str/bytes/None/enum values become jit
    statics (they feed Python control flow in user modules); arrays, numbers,
    and containers stay traced."""
    import enum

    is_static = lambda v: isinstance(v, (bool, str, bytes, enum.Enum)) or v is None
    traced_args = tuple(None if is_static(a) else a for a in args)
    static_args = tuple((i, a) for i, a in enumerate(args) if is_static(a))
    traced_kw = {k: v for k, v in kwargs.items() if not is_static(v)}
    static_kw = tuple(sorted((k, v) for k, v in kwargs.items() if is_static(v)))
    return traced_args, static_args, traced_kw, static_kw


def _merge_static_call(args, kwargs, static_args, static_kw):
    args = list(args)
    for i, v in static_args:
        args[i] = v
    return tuple(args), dict(kwargs, **dict(static_kw))


def _looks_like_schedule(fn) -> bool:
    """True if ``fn`` behaves like an optax schedule: step -> scalar lr.
    Guards prepare()'s pass 3 from silently wrapping stray callables (e.g. a
    loss function passed positionally) as schedulers.

    Detection order (to avoid executing user code where possible):
    1. the signature is checked, so multi-arg callables (loss functions,
       factories) are rejected without executing them;
    2. single-arg callables whose ``__module__``/``__wrapped__`` come from
       optax are accepted without probing (covers every optax.schedules
       factory);
    3. remaining single-argument callables ARE probed with ``fn(0)`` — a
       side-effecting closure will observe a fake step-0 call. Pass such
       callables through ``Accelerator.prepare_scheduler`` explicitly to
       skip prepare()'s probing entirely."""
    import inspect

    try:
        sig = inspect.signature(fn)
        sig.bind(0)  # must accept exactly one positional argument
    except TypeError:
        return False
    except (ValueError, RuntimeError):  # builtins without signatures: probe
        pass
    # single-arg callables minted by optax (schedule factories return
    # closures from optax.schedules.*) are schedules — skip the probe. The
    # signature check above still ran, so optax LOSS functions (2+ args)
    # were already rejected without this fast path ever seeing them.
    probed = fn.func if isinstance(fn, functools.partial) else getattr(fn, "__wrapped__", fn)
    if (getattr(probed, "__module__", "") or "").split(".")[0] == "optax":
        return True
    try:
        out = fn(0)
    except Exception:
        return False
    if isinstance(out, bool):  # a predicate, not a learning rate
        return False
    if isinstance(out, (int, float)):
        return True
    return hasattr(out, "shape") and tuple(getattr(out, "shape", (1,))) == ()


def _cast_float_outputs(outputs, dtype):
    return recursively_apply(
        lambda t: t.astype(dtype) if jnp.issubdtype(t.dtype, jnp.floating) else t, outputs
    )


def _batch_to_call(batch):
    if isinstance(batch, dict):
        return (), batch
    if isinstance(batch, (tuple, list)):
        return tuple(batch), {}
    return (batch,), {}


def _extract_lm_batch(args, kwargs, argnames=("input_ids", "labels")):
    """(input_ids, labels) from an LM call, or (None, None) when the call
    carries ANYTHING else (positions, deterministic, masks…) — a manual
    pipeline backward only covers the plain (input_ids, labels) signature,
    and silently dropping extra inputs would diverge from AD.

    ``argnames`` is the MODEL's positional parameter order (taken from its
    call signature at engine init): positional args are bound by name
    before the check, so a tuple batch against Seq2SeqLM's
    (input_ids, decoder_input_ids, ...) signature maps args[1] to
    decoder_input_ids — and is routed to AD — instead of being misread as
    labels."""
    named = {}
    for i, a in enumerate(args):
        if i >= len(argnames):
            return None, None
        named[argnames[i]] = a
    named.update(kwargs)
    if any(k not in ("input_ids", "labels") for k in named):
        return None, None
    return named.get("input_ids"), named.get("labels")


class Accelerator:
    """The user façade (reference accelerator.py:160)."""

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        log_with=None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        sharding_config: Optional[ShardingConfig] = None,
        compile_plugin: Optional[CompilePlugin] = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: Optional[list] = None,
        rng_types: Optional[list] = None,
        loss_fn: Optional[Callable] = None,
        telemetry=None,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # kwargs handlers (reference accelerator.py:347-381)
        self.scaler_handler = None
        self.init_handler = None
        self.autocast_handler = None
        self.profile_handler = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, InitProcessGroupKwargs):
                self.init_handler = handler
            elif isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler

        self.compile_plugin = compile_plugin or CompilePlugin()
        self.compile_plugin.apply_cache()

        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            sharding_config=sharding_config,
            _from_accelerator=True,
        )
        if self.scaler_handler is not None:
            self.state.precision.grad_scaler = self.scaler_handler
        if self.state.mixed_precision == "fp8":
            _warn_fp8_without_mxu_once(self.state.device)

        if gradient_accumulation_plugin is None:
            gradient_accumulation_plugin = GradientAccumulationPlugin(
                num_steps=int(os.environ.get("ACCELERATE_TPU_GRADIENT_ACCUMULATION_STEPS",
                                             gradient_accumulation_steps))
            )
        self.gradient_state = GradientState(gradient_accumulation_plugin=gradient_accumulation_plugin)

        self.dataloader_config = dataloader_config or DataLoaderConfiguration(split_batches=split_batches)
        self.device_placement = device_placement
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["jax"]
        self.loss_fn = loss_fn

        self._engines: list[TrainEngine] = []
        self._models: list[PreparedModel] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list = []
        self._custom_objects: list = []
        self._load_model_state_pre_hook = {}
        self._save_model_state_pre_hook = {}
        self.step = 0
        self.flag_tensor = None

        from .tracking import filter_trackers

        self.log_with = filter_trackers(log_with, self.logging_dir)
        self.trackers: list = []

        # runtime telemetry (docs/telemetry.md): `telemetry=` takes a
        # TelemetryConfig (or True for defaults); None defers to the
        # ATT_TELEMETRY env gate. Disabled -> self.telemetry is None and the
        # engine step paths stay on their zero-overhead fast path.
        from .telemetry import TelemetrySession, resolve_config

        tcfg = resolve_config(telemetry)
        self.telemetry = TelemetrySession(tcfg, accelerator=self) if tcfg else None

    # ------------------------------------------------------------------
    # state passthroughs (reference accelerator.py properties)
    # ------------------------------------------------------------------

    @property
    def distributed_type(self):
        return self.state.distributed_type

    @property
    def num_processes(self):
        return self.state.num_processes

    @property
    def process_index(self):
        return self.state.process_index

    @property
    def local_process_index(self):
        return self.state.local_process_index

    @property
    def device(self):
        return self.state.device

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def is_main_process(self):
        return self.state.is_main_process

    @property
    def is_local_main_process(self):
        return self.state.is_local_main_process

    @property
    def is_last_process(self):
        return self.state.is_last_process

    @property
    def mixed_precision(self):
        return self.state.mixed_precision

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def save_iteration(self):
        return self.project_configuration.iteration

    @property
    def sync_gradients(self):
        return self.gradient_state.sync_gradients

    @property
    def gradient_accumulation_steps(self):
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def optimizer_step_was_skipped(self):
        return any(opt.step_was_skipped for opt in self._optimizers)

    def on_main_process(self, function):
        return self.state.on_main_process(function)

    def on_local_main_process(self, function):
        return self.state.on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        return self.state.on_process(function, process_index)

    def on_last_process(self, function):
        return self.state.on_last_process(function)

    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    @contextlib.contextmanager
    def main_process_first(self):
        with self.state.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.state.local_main_process_first():
            yield

    def split_between_processes(self, inputs, apply_padding=False):
        return self.state.split_between_processes(inputs, apply_padding=apply_padding)

    def print(self, *args, **kwargs):
        self.state.print(*args, **kwargs)

    # ------------------------------------------------------------------
    # prepare (reference accelerator.py:1211)
    # ------------------------------------------------------------------

    def prepare(self, *args, device_placement=None):
        """Dispatch each object to its _prepare_* (two-pass like the
        reference: models first so optimizers can attach to engines)."""
        result = list(args)
        # pass 1: models
        for i, obj in enumerate(result):
            if isinstance(obj, Model) or _is_flax_module(obj):
                result[i] = self.prepare_model(obj)
        # pass 2: everything else
        for i, obj in enumerate(result):
            if isinstance(obj, optax.GradientTransformation):
                result[i] = self.prepare_optimizer(obj)
            elif _is_dataloader_like(obj):
                result[i] = self.prepare_data_loader(obj)
        # pass 3: schedules (need prepared optimizers)
        for i, obj in enumerate(result):
            if callable(obj) and not isinstance(
                obj, (PreparedModel, AcceleratedOptimizer, AcceleratedScheduler, Model)
            ) and not _is_dataloader_like(obj) and not isinstance(obj, optax.GradientTransformation):
                if not _looks_like_schedule(obj):
                    raise TypeError(
                        f"prepare() received a callable ({obj!r}) that is not an "
                        "optax schedule (schedule(step:int) must return a scalar "
                        "learning rate; single-argument candidates are probed "
                        "with step=0). Loss functions belong on the model "
                        "(Model(..., loss_fn=...)) or Accelerator(loss_fn=...), "
                        "not in prepare()."
                    )
                result[i] = self.prepare_scheduler(obj)
        return result[0] if len(result) == 1 else tuple(result)

    def prepare_model(self, model: Union[Model, Any], device_placement=None, evaluation_mode=False) -> PreparedModel:
        if _is_flax_module(model):
            raise ValueError(
                "Pass `accelerate_tpu.Model(flax_module, variables)` so prepare() "
                "has the parameters (JAX separates module and params)."
            )
        if model.loss_fn is None and self.loss_fn is not None:
            model.loss_fn = self.loss_fn
        if self.mixed_precision == "fp8":
            model.definition = _enable_fp8(model.definition)
        engine = TrainEngine(model, self)
        self._engines.append(engine)
        if self.telemetry is not None:
            self.telemetry.attach_engine(engine)
        prepared = PreparedModel(engine)
        if evaluation_mode:
            prepared.eval()
        self._models.append(prepared)
        return prepared

    def prepare_optimizer(self, optimizer: optax.GradientTransformation, device_placement=None) -> AcceleratedOptimizer:
        engine = self._engines[len(self._optimizers)] if len(self._engines) > len(self._optimizers) else (
            self._engines[-1] if self._engines else None
        )
        wrapped = AcceleratedOptimizer(optimizer, engine=engine)
        if engine is not None:
            engine.attach_optimizer(optimizer)
        self._optimizers.append(wrapped)
        return wrapped

    def prepare_scheduler(self, schedule: Callable) -> AcceleratedScheduler:
        wrapped = AcceleratedScheduler(
            schedule,
            optimizers=self._optimizers,
            split_batches=self.dataloader_config.split_batches,
            step_with_optimizer=self.step_scheduler_with_optimizer,
        )
        for engine in self._engines:
            if engine.schedule is None:
                engine.schedule = schedule
        self._schedulers.append(wrapped)
        return wrapped

    def prepare_data_loader(self, data_loader, device_placement=None, slice_fn_for_dispatch=None):
        prepared = prepare_data_loader(
            data_loader,
            mesh=self.state.mesh if (device_placement if device_placement is not None else self.device_placement) else None,
            rng_types=self.rng_types,
            config=self.dataloader_config,
        )
        self._dataloaders.append(prepared)
        return prepared

    # ------------------------------------------------------------------
    # the training contract
    # ------------------------------------------------------------------

    def backward(self, loss=None, **kwargs):
        """Reference accelerator.py:2164. The loss value is informational
        (grads were computed at the model call); accumulation scaling by
        1/num_steps happens here like the reference's loss division."""
        for engine in self._engines:
            if engine._pending_grads is not None:
                engine.backward(loss)

    def clip_grad_norm_(self, parameters=None, max_norm: float = 1.0, norm_type: int = 2):
        """Reference accelerator.py:2292. Returns the global grad norm."""
        if norm_type != 2:
            raise ValueError("only L2 grad clipping is supported on TPU")
        norms = [e.clip_grad_norm(max_norm) for e in self._engines]
        return norms[0] if len(norms) == 1 else norms

    def clip_grad_value_(self, parameters=None, clip_value: float = 1.0):
        raise NotImplementedError(
            "clip_grad_value_ is not supported; use clip_grad_norm_ "
            "(value clipping breaks GSPMD gradient fusion)."
        )

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Reference accelerator.py:931-1088: toggles sync_gradients based on
        the step counter / dataloader end."""
        self._do_sync()
        yield

    def _do_sync(self):
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients(
                (self.step % self.gradient_state.num_steps) == 0
                or self.gradient_state.sync_each_batch
            )

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """Under GSPMD grad reduction happens inside the fused update, so
        accumulating locally is already communication-free; this context just
        forces sync_gradients False for parity (reference accelerator.py:994)."""
        old = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(old)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """DDP Join parity (reference accelerator.py:1091). With global-batch
        SPMD feeding every process always sees the same number of batches, so
        this is a no-op wrapper (even_batches override included for parity)."""
        if even_batches is not None:
            for dl in self._dataloaders:
                dl.even_batches = even_batches
        yield

    @contextlib.contextmanager
    def autocast(self, autocast_handler: Optional[AutocastKwargs] = None):
        """Parity context (reference accelerator.py:3386): precision is a
        property of the staged computation, so nothing to switch here."""
        yield

    def replica_wire_bytes(self):
        """Per-step DCN wire bytes under the active gradient-compression
        config (see TrainEngine.replica_wire_bytes). Compare configs:

        >>> acc.replica_wire_bytes()                     # {"bytes": ...}
        >>> TrainEngine.replica_wire_bytes(params, "bfloat16")
        >>> TrainEngine.replica_wire_bytes(params, grad_compression_rank=4)
        """
        if not self._engines:
            raise RuntimeError("prepare(model, optimizer) before replica_wire_bytes")
        eng = self._engines[-1]
        sc = self.state.sharding_config
        return eng.replica_wire_bytes(
            eng.params,
            getattr(sc, "grad_compression_dtype", None),
            getattr(sc, "grad_compression_rank", None),
        )

    def build_train_step(
        self,
        loss_fn: Optional[Callable] = None,
        micro_steps: Optional[int] = None,
        steps_per_call: Optional[int] = None,
    ):
        """The fused-perf path: one XLA computation for the whole optimizer
        step (micro-batch scan + clip + update). Idiomatic-JAX users should
        prefer this over the eager-parity loop. ``steps_per_call=K`` scans K
        full optimizer steps in one executable (batch leaves gain a leading
        [K, ...] axis) — amortizes per-dispatch latency for small models."""
        if not self._engines:
            raise RuntimeError("prepare(model, optimizer) before build_train_step")
        return self._engines[-1].build_train_step(
            loss_fn=loss_fn, micro_steps=micro_steps, steps_per_call=steps_per_call
        )

    def audit_entrypoints(self, step, batch) -> list:
        """Static-audit specs for a step built by :meth:`build_train_step`
        (see :meth:`TrainEngine.audit_entrypoints`)."""
        if not self._engines:
            return []
        return self._engines[-1].audit_entrypoints(step, batch)

    # ------------------------------------------------------------------
    # collectives façade (reference accelerator.py:2408-2608)
    # ------------------------------------------------------------------

    def gather(self, tensor):
        return gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather + drop the tail samples duplicated by even_batches padding
        (reference accelerator.py:2408-2480, driven by GradientState.remainder)."""
        try:
            recursively_apply(lambda x: x, input_data, error_on_other_type=True)
            all_tensors = True
        except TypeError:
            all_tensors = False
        if use_gather_object or not all_tensors:
            data = gather_object(input_data)
        else:
            data = self.gather(input_data)
        if self.gradient_state.end_of_dataloader and self.gradient_state.remainder > 0:
            def _trim(t):
                # only batched leaves carry padding; scalars (e.g. a mean
                # loss) pass through untouched
                if getattr(t, "ndim", 0) == 0:
                    return t
                return t[: self.gradient_state.remainder]

            return recursively_apply(_trim, data)
        return data

    def reduce(self, tensor, reduction="sum", scale=1.0):
        return reduce(tensor, reduction, scale)

    def pad_across_processes(self, tensor, dim=0, pad_index=0, pad_first=False):
        return pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        if isinstance(model, PreparedModel):
            return model.unwrap()
        return model

    def prepare_for_eval(self, batch, batch_dim: int = 0):
        """Place an eval batch the same way prepared dataloaders do.
        ``batch_dim=1`` for a stacked [K, batch, ...] multi-step batch
        (``build_train_step(steps_per_call=K)``): steps axis replicated,
        batch axis sharded over the data mesh axes."""
        from .utils.operations import make_global_batch

        return make_global_batch(batch, self.state.mesh, batch_dim=batch_dim)

    # ------------------------------------------------------------------
    # trigger (coordinated breakpoint; reference accelerator.py:2198-2255)
    # ------------------------------------------------------------------

    def set_trigger(self):
        self.flag_tensor = True

    def check_trigger(self) -> bool:
        flags = gather_object([1 if self.flag_tensor else 0])
        if any(flags):
            self.flag_tensor = False
            return True
        return False

    # ------------------------------------------------------------------
    # trackers (reference accelerator.py:2610-2737)
    # ------------------------------------------------------------------

    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: dict = {}):
        from .tracking import resolve_trackers

        self.trackers = resolve_trackers(self.log_with, project_name, self.logging_dir, init_kwargs)
        if config is not None:
            for tracker in self.trackers:
                tracker.store_init_configuration(config)

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        from .tracking import GeneralTracker

        return GeneralTracker(_blank=True)

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: dict = {}):
        for tracker in self.trackers:
            tracker.log(values, step=step, **log_kwargs.get(tracker.name, {}))

    def log_system_metrics(self, step: Optional[int] = None, extra: Optional[dict] = None,
                           log_kwargs: dict = {}) -> dict:
        """Flush the telemetry rollup (step time, tokens/s, MFU, data-wait
        split, compile/cache activity, memory, precision health — see
        docs/telemetry.md for the glossary) through every configured
        tracker, and return it. Requires ``telemetry=`` to be enabled."""
        if self.telemetry is None:
            raise RuntimeError(
                "telemetry is not enabled; pass telemetry=TelemetryConfig(...) "
                "(or True) to Accelerator, or set ATT_TELEMETRY=1."
            )
        values = self.telemetry.rollup()
        if extra:
            values = {**values, **extra}
        if values:
            if step is None:
                step = values.get("sys/step")
            self.log(values, step=step, log_kwargs=log_kwargs)
        return values

    def prometheus_metrics(self) -> str:
        """The live telemetry rollup + SLO histograms as Prometheus text
        exposition — what the scrape thread serves
        (``TelemetryConfig(exporter_port=...)`` / ``ATT_TELEMETRY_PORT``);
        exposed directly for custom health endpoints. Requires
        ``telemetry=`` to be enabled."""
        if self.telemetry is None:
            raise RuntimeError(
                "telemetry is not enabled; pass telemetry=TelemetryConfig(...) "
                "(or True) to Accelerator, or set ATT_TELEMETRY=1."
            )
        from .telemetry.exporter import prometheus_text

        return prometheus_text(self.telemetry)

    def end_training(self):
        if self.telemetry is not None:
            self.telemetry.close()
        for tracker in self.trackers:
            tracker.finish()

    # ------------------------------------------------------------------
    # save / load (reference accelerator.py:2739-3218) — checkpointing.py
    # ------------------------------------------------------------------

    def save(self, obj, f, safe_serialization: bool = True):
        from .utils.other import save as _save

        _save(obj, f, save_on_each_node=self.project_configuration.save_on_each_node,
              safe_serialization=safe_serialization)

    def save_model(self, model, save_directory, max_shard_size="10GB", safe_serialization=True):
        from .checkpointing import save_model_weights

        save_model_weights(model, save_directory, max_shard_size=max_shard_size,
                           safe_serialization=safe_serialization)

    def register_for_checkpointing(self, *objects):
        invalid = [obj for obj in objects if not (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict"))]
        if invalid:
            raise ValueError(
                f"All `objects` must include a `state_dict` and `load_state_dict` function to be stored: {invalid}"
            )
        self._custom_objects.extend(objects)

    def register_save_state_pre_hook(self, hook):
        import uuid

        key = uuid.uuid4()
        self._save_model_state_pre_hook[key] = hook
        return _RemovableHandle(self._save_model_state_pre_hook, key)

    def register_load_state_pre_hook(self, hook):
        import uuid

        key = uuid.uuid4()
        self._load_model_state_pre_hook[key] = hook
        return _RemovableHandle(self._load_model_state_pre_hook, key)

    def save_state(self, output_dir: Optional[str] = None, safe_serialization: bool = True, **save_model_func_kwargs):
        from .checkpointing import save_accelerator_state

        if self.project_configuration.automatic_checkpoint_naming:
            output_dir = os.path.join(self.project_dir, "checkpoints")
        os.makedirs(output_dir, exist_ok=True)
        if self.project_configuration.automatic_checkpoint_naming:
            folders = [os.path.join(output_dir, folder) for folder in os.listdir(output_dir)]
            if (
                self.project_configuration.total_limit is not None
                and (len(folders) + 1 > self.project_configuration.total_limit)
                and self.is_main_process
            ):
                folders.sort(key=lambda f: int(f.rsplit("_", 1)[-1]) if f.rsplit("_", 1)[-1].isdigit() else -1)
                for folder in folders[: len(folders) + 1 - self.project_configuration.total_limit]:
                    import shutil

                    shutil.rmtree(folder, ignore_errors=True)
            output_dir = os.path.join(output_dir, f"checkpoint_{self.save_iteration}")
            if os.path.exists(output_dir):
                raise ValueError(
                    f"Checkpoint directory {output_dir} ({self.save_iteration}) already "
                    "exists. Please manually override `self.save_iteration` with what "
                    "iteration to start with."
                )
            self.wait_for_everyone()
        os.makedirs(output_dir, exist_ok=True)
        logger.info(f"Saving current state to {output_dir}")

        for hook in self._save_model_state_pre_hook.values():
            hook(self._models, [], output_dir)

        path = save_accelerator_state(
            output_dir,
            engines=self._engines,
            schedulers=self._schedulers,
            dataloaders=self._dataloaders,
            custom_objects=self._custom_objects,
            step=self.step,
            safe_serialization=safe_serialization,
        )
        self.project_configuration.iteration += 1
        return path

    def load_state(self, input_dir: Optional[str] = None, **load_model_func_kwargs):
        from .checkpointing import load_accelerator_state

        if input_dir is None and self.project_configuration.automatic_checkpoint_naming:
            base = os.path.join(self.project_dir, "checkpoints")
            folders = sorted(
                os.listdir(base), key=lambda f: int(f.rsplit("_", 1)[-1]) if f.rsplit("_", 1)[-1].isdigit() else -1
            )
            input_dir = os.path.join(base, folders[-1])
        logger.info(f"Loading states from {input_dir}")

        for hook in self._load_model_state_pre_hook.values():
            hook(self._models, [], input_dir)

        override_step = load_accelerator_state(
            input_dir,
            engines=self._engines,
            schedulers=self._schedulers,
            dataloaders=self._dataloaders,
            custom_objects=self._custom_objects,
        )
        if override_step is not None:
            self.step = override_step

    def get_state_dict(self, model, unwrap=True):
        """Full (host-replicated) variables of a prepared model — the
        FSDP FULL_STATE_DICT consolidation analog (reference :3291-3348)."""
        if isinstance(model, PreparedModel):
            variables = model.state_dict()
        elif isinstance(model, Model):
            variables = model.variables
        else:
            variables = model
        from .utils.serialization import _to_numpy

        return jax.tree_util.tree_map(_to_numpy, variables)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return _skip_first_batches(dataloader, num_batches)

    def free_memory(self, *objects):
        """Reference :3219. Drops engine/device state references + caches."""
        from .utils.memory import release_memory

        objects = release_memory(*objects)
        if self.telemetry is not None:
            self.telemetry._engines.clear()
        self._engines.clear()
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self.step = 0
        jax.clear_caches()
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    def profile(self, profile_handler: Optional[ProfileKwargs] = None):
        handler = profile_handler or self.profile_handler or ProfileKwargs()
        return handler.build(suffix=str(self.process_index))

    @contextlib.contextmanager
    def local_sgd(self, *args, **kwargs):  # pragma: no cover - see local_sgd.py
        from .local_sgd import LocalSGD

        with LocalSGD(self, *args, **kwargs) as ctx:
            yield ctx

    def __repr__(self):
        return f"Accelerator(state={self.state!r})"


class _RemovableHandle:
    def __init__(self, registry, key):
        self.registry = registry
        self.key = key

    def remove(self):
        self.registry.pop(self.key, None)


def _is_dataloader_like(obj) -> bool:
    from .data import DataLoader

    if isinstance(obj, (DataLoader, DataLoaderShard, DataLoaderDispatcher)):
        return True
    return type(obj).__module__.startswith("torch.utils.data")
