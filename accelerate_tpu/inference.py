"""Pipeline-parallel inference — the `prepare_pippy` analog.

The reference wraps a torch model with `torch.distributed.pipelining`
(`prepare_pippy`, /root/reference/src/accelerate/inference.py:73-184):
auto split points, `ScheduleGPipe`, rank0-feeds/last-rank-returns, batch
padded to the chunk count. On TPU the same capability is a re-wrap: take a
(possibly non-PP-trained) scan-stacked DecoderLM, re-layout its layer stack
into stage-major [S, L/S, ...] leaves sharded over the mesh "stage" axis,
and jit the GPipe microbatch schedule (parallel/pipeline.py). Every host
holds the replicated output ("last rank returns + broadcast" semantics with
zero extra code, since GSPMD outputs are global arrays).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger

logger = get_logger(__name__)


class PipelinedModel:
    """Callable wrapper running pipelined forward passes.

    __call__(input_ids, ...) pads the batch up to a microbatch multiple
    (reference inference.py:110-112), runs the pipelined jit, and slices the
    padding back off.
    """

    def __init__(self, model_def, params, num_microbatches: int):
        self.model_def = model_def
        self.params = params
        self.num_microbatches = num_microbatches
        self._jit = jax.jit(
            lambda p, ids, kw, s_kw: model_def.apply(
                {"params": p}, ids, **dict(kw, **dict(s_kw))
            )["logits"],
            static_argnums=(3,),
        )

    def __call__(self, input_ids, **kwargs):
        from .accelerator import _split_static_call

        ids = jnp.asarray(input_ids)
        batch = ids.shape[0]
        target = -(-batch // self.num_microbatches) * self.num_microbatches
        if target != batch:
            pad = jnp.tile(ids[:1], (target - batch,) + (1,) * (ids.ndim - 1))
            ids = jnp.concatenate([ids, pad], axis=0)
            # batch-dim kwargs (e.g. attention masks) must pad with the batch
            kwargs = {
                k: jnp.concatenate(
                    [jnp.asarray(v), jnp.tile(jnp.asarray(v)[:1], (target - batch,) + (1,) * (jnp.asarray(v).ndim - 1))],
                    axis=0,
                )
                if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1 and jnp.asarray(v).shape[0] == batch
                else v
                for k, v in kwargs.items()
            }
        _, _, traced_kw, static_kw = _split_static_call((), kwargs)
        logits = self._jit(self.params, ids, traced_kw, static_kw)
        return logits[:batch]

    def eval(self):
        return self

    def train(self, mode: bool = True):
        if mode:
            raise RuntimeError("prepare_pippy wraps the model for inference only")
        return self  # train(False) == eval()


def prepare_pippy(
    model,
    num_stages: Optional[int] = None,
    num_microbatches: Optional[int] = None,
    mesh=None,
    example_args: tuple = (),
) -> PipelinedModel:
    """Split a scan-stacked DecoderLM over pipeline stages for inference
    (capability parity: reference inference.py:124's prepare_pippy).

    ``model`` is an accelerate_tpu ``Model`` (definition + variables) or a
    ``(definition, variables)`` pair; the definition must be a DecoderLM with
    ``scan_layers=True`` (the auto-split analog: the layer scan IS the split
    point structure).
    """
    from .models import DecoderLM
    from .parallel.sharding import (
        infer_param_sharding,
        shard_params,
        unbox_params,
    )
    from .parallel.pipeline import remap_params_to_pipeline
    from .state import AcceleratorState
    from .utils.dataclasses import ShardingConfig

    if isinstance(model, tuple):
        definition, variables = model
    else:
        definition, variables = model.definition, {"params": model.params}
    if not isinstance(definition, DecoderLM):
        raise TypeError(
            "prepare_pippy supports DecoderLM-family models (scan-stacked "
            f"blocks define the stage split); got {type(definition).__name__}"
        )
    cfg = definition.config
    if not cfg.scan_layers and cfg.pipeline_stages <= 1:
        raise ValueError("prepare_pippy needs scan_layers=True (stage split points)")

    state = AcceleratorState()
    mesh = mesh if mesh is not None else state.mesh
    if num_stages is None:
        num_stages = mesh.shape.get("stage", 1)
        if num_stages <= 1:
            raise ValueError(
                "prepare_pippy found no 'stage' axis in the mesh — configure "
                "ShardingConfig(pipeline_parallel=k) (or pass num_stages "
                "explicitly for schedule testing without a stage axis); a "
                "forced schedule on an unsplit mesh only adds bubble overhead"
            )
    if num_microbatches is None:
        num_microbatches = num_stages
    if cfg.num_layers % num_stages != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by num_stages={num_stages}"
        )

    pipe_cfg = dataclasses.replace(
        cfg, pipeline_stages=num_stages, pipeline_microbatches=num_microbatches
    )
    pipe_def = DecoderLM(pipe_cfg, mesh=mesh)

    # template tree (shapes only) for the pipeline layout, then re-layout the
    # trained params into it
    dense_raw, _ = unbox_params(variables["params"])
    if example_args:
        trace_ids = jnp.zeros(jnp.asarray(example_args[0]).shape, jnp.int32)
    else:
        trace_ids = jnp.zeros((num_microbatches, 8), jnp.int32)
    template = jax.eval_shape(
        lambda: pipe_def.init(jax.random.PRNGKey(0), trace_ids)
    )
    template_raw, template_axes = unbox_params(template["params"])
    pipe_params = remap_params_to_pipeline(dense_raw, template_raw, num_stages)

    shardings = infer_param_sharding(
        pipe_params, mesh, state.sharding_config or ShardingConfig(), template_axes
    )
    pipe_params = shard_params(pipe_params, shardings)
    logger.info(
        "prepare_pippy: %d stages x %d layers/stage, %d microbatches",
        num_stages,
        cfg.num_layers // num_stages,
        num_microbatches,
    )
    return PipelinedModel(pipe_def, pipe_params, num_microbatches)
