// att_runtime — native host-runtime primitives for accelerate_tpu.
//
// The reference framework has no native code of its own (SURVEY preamble:
// every native capability comes from torch/NCCL/torch_xla). In a JAX
// framework the device path is XLA; what remains host-side and
// performance-critical is IO and batch assembly, both GIL-bound in pure
// Python:
//
//   * att_parallel_read  — multithreaded pread of tensor segments from a
//     checkpoint file straight into destination buffers (drives
//     serialization.load_flat_dict; checkpoint-load latency is a headline
//     benchmark: reference big_model_inference loads are 8.7-112s).
//   * att_parallel_memcpy — multithreaded scatter/gather copy used by the
//     prefetcher to assemble per-host batch buffers while the previous
//     step runs on device (ctypes releases the GIL around the call).
//   * att_ring_* — a slots/condvar ring buffer giving the double-buffered
//     producer/consumer contract (pallas_guide.md double-buffering pattern,
//     applied host-side).
//   * att_quantize_group — single-pass per-group weight quantization
//     (linear int8/int4 + NF4) straight from the checkpoint's bf16/fp32
//     bytes. Quantize-on-load halves/quarters the bytes crossing the
//     host->device link (the TTFT bottleneck); the numpy version costs
//     ~7 full passes over fp32 temporaries, this one reads the source once
//     and writes packed bytes + scales once.
//
// Pure C ABI on purpose: loaded via ctypes, no Python.h / pybind11
// dependency, trivially built with `g++ -O3 -shared -fPIC -pthread`.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Segment {
  uint64_t file_offset;
  uint64_t size;
  unsigned char *dst;
};

// Split [0, count) into contiguous chunks and run fn(chunk_begin, chunk_end)
// on num_threads workers.
void parallel_for(int count, int num_threads, void (*body)(int, void *), void *ctx) {
  if (num_threads < 1) num_threads = 1;
  if (num_threads > count) num_threads = count > 0 ? count : 1;
  std::atomic<int> next{0};
  std::vector<std::thread> workers;
  workers.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) {
    workers.emplace_back([&]() {
      int i;
      while ((i = next.fetch_add(1)) < count) body(i, ctx);
    });
  }
  for (auto &w : workers) w.join();
}

// NormalFloat4 code (QLoRA) — must match utils/quantization.NF4_CODE.
const float kNf4Code[16] = {
    -1.0f, -0.6961928009986877f, -0.5250730514526367f, -0.39491748809814453f,
    -0.28444138169288635f, -0.18477343022823334f, -0.09105003625154495f, 0.0f,
    0.07958029955625534f, 0.16093020141124725f, 0.24611230194568634f,
    0.33791524171829224f, 0.4407098591327667f, 0.5626170039176941f,
    0.7229568362236023f, 1.0f};

// Midpoints between adjacent NF4 code levels; index(x) = #(x > mid[t]) —
// identical to np.searchsorted(mids, x) and to the old binary search
// (equality rounds down in all three).
const float kNf4Mid[15] = {
    0.5f * (kNf4Code[0] + kNf4Code[1]),   0.5f * (kNf4Code[1] + kNf4Code[2]),
    0.5f * (kNf4Code[2] + kNf4Code[3]),   0.5f * (kNf4Code[3] + kNf4Code[4]),
    0.5f * (kNf4Code[4] + kNf4Code[5]),   0.5f * (kNf4Code[5] + kNf4Code[6]),
    0.5f * (kNf4Code[6] + kNf4Code[7]),   0.5f * (kNf4Code[7] + kNf4Code[8]),
    0.5f * (kNf4Code[8] + kNf4Code[9]),   0.5f * (kNf4Code[9] + kNf4Code[10]),
    0.5f * (kNf4Code[10] + kNf4Code[11]), 0.5f * (kNf4Code[11] + kNf4Code[12]),
    0.5f * (kNf4Code[12] + kNf4Code[13]), 0.5f * (kNf4Code[13] + kNf4Code[14]),
    0.5f * (kNf4Code[14] + kNf4Code[15])};

struct QuantCtx {
  const unsigned char *src;
  int src_dtype; // 0 = fp32, 1 = bf16
  uint64_t k, n, group;
  int bits;
  int mode; // 0 = linear, 1 = nf4
  int8_t *out_q;
  float *out_scale;
};

// Branch-free quantized index for one value against the sorted NF4
// midpoints: idx = #(midpoints < x). The invariant 15-iteration inner loop
// auto-vectorizes (15 cmp+sub per SIMD lane group), unlike a binary search.
inline int nf4_index_sum(float x, const float *mids) {
  int idx = 0;
  for (int t = 0; t < 15; ++t) idx += x > mids[t];
  return idx;
}

void quant_one_group(int g, void *vctx) {
  QuantCtx &c = *static_cast<QuantCtx *>(vctx);
  const uint64_t r0 = static_cast<uint64_t>(g) * c.group;
  const uint64_t rows = c.group;
  const uint64_t n = c.n;
  const float qmax = c.bits == 8 ? 127.0f : 7.0f;

  // stage the group as fp32 ONCE (one vectorizable widen for bf16 sources,
  // a straight copy for fp32) — the old per-element load_src re-converted
  // every value twice behind a dtype branch, which blocked vectorization
  // and capped the kernel at ~250 MB/s on one core
  thread_local std::vector<float> buf;
  buf.resize(rows * n);
  if (c.src_dtype == 0) {
    std::memcpy(buf.data(), reinterpret_cast<const float *>(c.src) + r0 * n,
                rows * n * sizeof(float));
  } else {
    const uint16_t *s = reinterpret_cast<const uint16_t *>(c.src) + r0 * n;
    uint32_t *d = reinterpret_cast<uint32_t *>(buf.data());
    for (uint64_t i = 0; i < rows * n; ++i)
      d[i] = static_cast<uint32_t>(s[i]) << 16;
  }

  // per-column absmax over the group's rows
  thread_local std::vector<float> amax;
  amax.assign(n, 0.0f);
  for (uint64_t r = 0; r < rows; ++r) {
    const float *row = buf.data() + r * n;
    for (uint64_t j = 0; j < n; ++j) {
      float a = std::fabs(row[j]);
      if (a > amax[j]) amax[j] = a;
    }
  }
  float *scale_row = c.out_scale + static_cast<uint64_t>(g) * n;
  thread_local std::vector<float> recip;
  recip.resize(n);
  for (uint64_t j = 0; j < n; ++j) {
    float s;
    if (c.mode == 1)
      s = amax[j] > 0 ? amax[j] : 1.0f; // nf4: normalize to [-1, 1]
    else
      s = amax[j] > 0 ? amax[j] / qmax : 1.0f;
    scale_row[j] = s;
    // reciprocal-MULTIPLY in the quantize pass (matches the numpy fallback,
    // which does the same, and XLA-on-TPU semantics — the MXU path lowers
    // fdiv to reciprocal+mul anyway); one divide per column instead of one
    // per element, and the inner loop becomes a pure FMA stream
    recip[j] = 1.0f / s;
  }

  if (c.bits == 8) {
    for (uint64_t r = 0; r < rows; ++r) {
      const float *row = buf.data() + r * n;
      int8_t *out_row = c.out_q + (r0 + r) * n;
      for (uint64_t j = 0; j < n; ++j) {
        float v = row[j] * recip[j];
        int iq = static_cast<int>(std::nearbyintf(v)); // half-even, like np.round
        if (iq > 127) iq = 127;
        if (iq < -127) iq = -127;
        out_row[j] = static_cast<int8_t>(iq);
      }
    }
    return;
  }
  // 4-bit: rows pack two-per-byte along dim 0 (row 2i -> low nibble,
  // row 2i+1 -> high nibble), exactly like the numpy packer. A group is
  // always a whole number of PACKED rows when group is even; with odd k
  // the final (pad) row is zero.
  const bool nf4 = c.mode == 1;
  thread_local std::vector<int8_t> qrow; // per-row indices, then packed
  qrow.resize(2 * n);
  for (uint64_t r = 0; r < rows; r += 2) {
    const float *row_lo = buf.data() + r * n;
    const bool has_hi = r0 + r + 1 < c.k && r + 1 < rows;
    const float *row_hi = has_hi ? buf.data() + (r + 1) * n : nullptr;
    int8_t *lo_q = qrow.data(), *hi_q = qrow.data() + n;
    if (nf4) {
      for (uint64_t j = 0; j < n; ++j)
        lo_q[j] = static_cast<int8_t>(nf4_index_sum(row_lo[j] * recip[j], kNf4Mid));
      if (has_hi)
        for (uint64_t j = 0; j < n; ++j)
          hi_q[j] = static_cast<int8_t>(nf4_index_sum(row_hi[j] * recip[j], kNf4Mid));
      else
        std::memset(hi_q, 0, n);
    } else {
      for (uint64_t j = 0; j < n; ++j) {
        int v = static_cast<int>(std::nearbyintf(row_lo[j] * recip[j]));
        if (v > 7) v = 7;
        if (v < -7) v = -7;
        lo_q[j] = static_cast<int8_t>(v);
      }
      if (has_hi)
        for (uint64_t j = 0; j < n; ++j) {
          int v = static_cast<int>(std::nearbyintf(row_hi[j] * recip[j]));
          if (v > 7) v = 7;
          if (v < -7) v = -7;
          hi_q[j] = static_cast<int8_t>(v);
        }
      else
        std::memset(hi_q, 0, n);
    }
    int8_t *out_row = c.out_q + ((r0 + r) / 2) * n;
    for (uint64_t j = 0; j < n; ++j)
      out_row[j] = static_cast<int8_t>((lo_q[j] & 0x0F) | ((hi_q[j] & 0x0F) << 4));
  }
}

} // namespace

extern "C" {

// Per-group symmetric quantization of a row-major [k, n] matrix along dim 0.
// src_dtype: 0 = fp32, 1 = bf16 (uint16 storage). mode: 0 = linear int
// (scale = amax/qmax), 1 = nf4 (scale = amax, output = codebook indices).
// bits 8: out_q is int8 [k, n]. bits 4: out_q is packed [(k+1)/2, n], two
// rows per byte (low nibble = even row). out_scale: fp32 [k/group, n].
// `group` must divide k and, for bits=4 with k > group, be even.
// Returns 0 on success.
int att_quantize_group(const unsigned char *src, int src_dtype, uint64_t k,
                       uint64_t n, uint64_t group, int bits, int mode,
                       int8_t *out_q, float *out_scale, int num_threads) {
  if (k == 0 || n == 0 || group == 0 || k % group != 0) return -1;
  if (bits != 8 && bits != 4) return -2;
  if (bits == 4 && group % 2 != 0 && k != group) return -3;
  QuantCtx ctx{src, src_dtype, k, n, group, bits, mode, out_q, out_scale};
  int groups = static_cast<int>(k / group);
  parallel_for(groups, num_threads, quant_one_group, &ctx);
  return 0;
}

// Read `count` segments of `path` into caller-provided buffers.
// Returns 0 on success, -errno-style negative on failure.
int att_parallel_read(const char *path, const uint64_t *file_offsets,
                      const uint64_t *sizes, unsigned char **dsts, int count,
                      int num_threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  std::atomic<int> err{0};
  struct Ctx {
    int fd;
    const uint64_t *off;
    const uint64_t *sz;
    unsigned char **dst;
    std::atomic<int> *err;
  } ctx{fd, file_offsets, sizes, dsts, &err};
  parallel_for(
      count, num_threads,
      [](int i, void *p) {
        auto *c = static_cast<Ctx *>(p);
        uint64_t remaining = c->sz[i];
        uint64_t off = c->off[i];
        unsigned char *dst = c->dst[i];
        while (remaining > 0) {
          ssize_t got = ::pread(c->fd, dst, remaining, (off_t)off);
          if (got <= 0) {
            c->err->store(-2);
            return;
          }
          remaining -= (uint64_t)got;
          off += (uint64_t)got;
          dst += got;
        }
      },
      &ctx);
  ::close(fd);
  return err.load();
}

void att_parallel_memcpy(unsigned char **dsts, const unsigned char **srcs,
                         const uint64_t *sizes, int count, int num_threads) {
  struct Ctx {
    unsigned char **dst;
    const unsigned char **src;
    const uint64_t *sz;
  } ctx{dsts, srcs, sizes};
  parallel_for(
      count, num_threads,
      [](int i, void *p) {
        auto *c = static_cast<Ctx *>(p);
        std::memcpy(c->dst[i], c->src[i], c->sz[i]);
      },
      &ctx);
}

// ---------------------------------------------------------------------------
// Ring buffer: fixed slot count, each slot a contiguous byte buffer.
// Producer: acquire_fill -> write via slot_ptr -> commit_fill.
// Consumer: acquire_read -> read -> release_read.
// ---------------------------------------------------------------------------

struct Ring {
  int slots;
  uint64_t slot_bytes;
  std::vector<std::vector<unsigned char>> storage;
  std::vector<int> state; // 0=free, 1=filling, 2=ready, 3=reading
  int fill_cursor = 0;
  int read_cursor = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable cv;
};

void *att_ring_create(int slots, uint64_t slot_bytes) {
  auto *r = new Ring();
  r->slots = slots;
  r->slot_bytes = slot_bytes;
  r->storage.resize(slots);
  for (auto &s : r->storage) s.resize(slot_bytes);
  r->state.assign(slots, 0);
  return r;
}

void att_ring_destroy(void *ring) { delete static_cast<Ring *>(ring); }

void att_ring_close(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->cv.notify_all();
}

// Returns slot index, or -1 if the ring is closed.
int att_ring_acquire_fill(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  std::unique_lock<std::mutex> lk(r->mu);
  int slot = r->fill_cursor;
  r->cv.wait(lk, [&] { return r->closed || r->state[slot] == 0; });
  if (r->closed) return -1;
  r->state[slot] = 1;
  r->fill_cursor = (slot + 1) % r->slots;
  return slot;
}

void att_ring_commit_fill(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->state[slot] = 2;
  }
  r->cv.notify_all();
}

int att_ring_acquire_read(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  std::unique_lock<std::mutex> lk(r->mu);
  int slot = r->read_cursor;
  r->cv.wait(lk, [&] { return r->closed || r->state[slot] == 2; });
  if (r->state[slot] != 2) return -1; // closed and nothing ready
  r->state[slot] = 3;
  r->read_cursor = (slot + 1) % r->slots;
  return slot;
}

void att_ring_release_read(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->state[slot] = 0;
  }
  r->cv.notify_all();
}

unsigned char *att_ring_slot_ptr(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  return r->storage[slot].data();
}

uint64_t att_ring_slot_bytes(void *ring) {
  return static_cast<Ring *>(ring)->slot_bytes;
}

} // extern "C"
