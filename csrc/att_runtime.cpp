// att_runtime — native host-runtime primitives for accelerate_tpu.
//
// The reference framework has no native code of its own (SURVEY preamble:
// every native capability comes from torch/NCCL/torch_xla). In a JAX
// framework the device path is XLA; what remains host-side and
// performance-critical is IO and batch assembly, both GIL-bound in pure
// Python:
//
//   * att_parallel_read  — multithreaded pread of tensor segments from a
//     checkpoint file straight into destination buffers (drives
//     serialization.load_flat_dict; checkpoint-load latency is a headline
//     benchmark: reference big_model_inference loads are 8.7-112s).
//   * att_parallel_memcpy — multithreaded scatter/gather copy used by the
//     prefetcher to assemble per-host batch buffers while the previous
//     step runs on device (ctypes releases the GIL around the call).
//   * att_ring_* — a slots/condvar ring buffer giving the double-buffered
//     producer/consumer contract (pallas_guide.md double-buffering pattern,
//     applied host-side).
//
// Pure C ABI on purpose: loaded via ctypes, no Python.h / pybind11
// dependency, trivially built with `g++ -O3 -shared -fPIC -pthread`.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Segment {
  uint64_t file_offset;
  uint64_t size;
  unsigned char *dst;
};

// Split [0, count) into contiguous chunks and run fn(chunk_begin, chunk_end)
// on num_threads workers.
void parallel_for(int count, int num_threads, void (*body)(int, void *), void *ctx) {
  if (num_threads < 1) num_threads = 1;
  if (num_threads > count) num_threads = count > 0 ? count : 1;
  std::atomic<int> next{0};
  std::vector<std::thread> workers;
  workers.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) {
    workers.emplace_back([&]() {
      int i;
      while ((i = next.fetch_add(1)) < count) body(i, ctx);
    });
  }
  for (auto &w : workers) w.join();
}

} // namespace

extern "C" {

// Read `count` segments of `path` into caller-provided buffers.
// Returns 0 on success, -errno-style negative on failure.
int att_parallel_read(const char *path, const uint64_t *file_offsets,
                      const uint64_t *sizes, unsigned char **dsts, int count,
                      int num_threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  std::atomic<int> err{0};
  struct Ctx {
    int fd;
    const uint64_t *off;
    const uint64_t *sz;
    unsigned char **dst;
    std::atomic<int> *err;
  } ctx{fd, file_offsets, sizes, dsts, &err};
  parallel_for(
      count, num_threads,
      [](int i, void *p) {
        auto *c = static_cast<Ctx *>(p);
        uint64_t remaining = c->sz[i];
        uint64_t off = c->off[i];
        unsigned char *dst = c->dst[i];
        while (remaining > 0) {
          ssize_t got = ::pread(c->fd, dst, remaining, (off_t)off);
          if (got <= 0) {
            c->err->store(-2);
            return;
          }
          remaining -= (uint64_t)got;
          off += (uint64_t)got;
          dst += got;
        }
      },
      &ctx);
  ::close(fd);
  return err.load();
}

void att_parallel_memcpy(unsigned char **dsts, const unsigned char **srcs,
                         const uint64_t *sizes, int count, int num_threads) {
  struct Ctx {
    unsigned char **dst;
    const unsigned char **src;
    const uint64_t *sz;
  } ctx{dsts, srcs, sizes};
  parallel_for(
      count, num_threads,
      [](int i, void *p) {
        auto *c = static_cast<Ctx *>(p);
        std::memcpy(c->dst[i], c->src[i], c->sz[i]);
      },
      &ctx);
}

// ---------------------------------------------------------------------------
// Ring buffer: fixed slot count, each slot a contiguous byte buffer.
// Producer: acquire_fill -> write via slot_ptr -> commit_fill.
// Consumer: acquire_read -> read -> release_read.
// ---------------------------------------------------------------------------

struct Ring {
  int slots;
  uint64_t slot_bytes;
  std::vector<std::vector<unsigned char>> storage;
  std::vector<int> state; // 0=free, 1=filling, 2=ready, 3=reading
  int fill_cursor = 0;
  int read_cursor = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable cv;
};

void *att_ring_create(int slots, uint64_t slot_bytes) {
  auto *r = new Ring();
  r->slots = slots;
  r->slot_bytes = slot_bytes;
  r->storage.resize(slots);
  for (auto &s : r->storage) s.resize(slot_bytes);
  r->state.assign(slots, 0);
  return r;
}

void att_ring_destroy(void *ring) { delete static_cast<Ring *>(ring); }

void att_ring_close(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->cv.notify_all();
}

// Returns slot index, or -1 if the ring is closed.
int att_ring_acquire_fill(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  std::unique_lock<std::mutex> lk(r->mu);
  int slot = r->fill_cursor;
  r->cv.wait(lk, [&] { return r->closed || r->state[slot] == 0; });
  if (r->closed) return -1;
  r->state[slot] = 1;
  r->fill_cursor = (slot + 1) % r->slots;
  return slot;
}

void att_ring_commit_fill(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->state[slot] = 2;
  }
  r->cv.notify_all();
}

int att_ring_acquire_read(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  std::unique_lock<std::mutex> lk(r->mu);
  int slot = r->read_cursor;
  r->cv.wait(lk, [&] { return r->closed || r->state[slot] == 2; });
  if (r->state[slot] != 2) return -1; // closed and nothing ready
  r->state[slot] = 3;
  r->read_cursor = (slot + 1) % r->slots;
  return slot;
}

void att_ring_release_read(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->state[slot] = 0;
  }
  r->cv.notify_all();
}

unsigned char *att_ring_slot_ptr(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  return r->storage[slot].data();
}

uint64_t att_ring_slot_bytes(void *ring) {
  return static_cast<Ring *>(ring)->slot_bytes;
}

} // extern "C"
