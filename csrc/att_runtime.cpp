// att_runtime — native host-runtime primitives for accelerate_tpu.
//
// The reference framework has no native code of its own (SURVEY preamble:
// every native capability comes from torch/NCCL/torch_xla). In a JAX
// framework the device path is XLA; what remains host-side and
// performance-critical is IO and batch assembly, both GIL-bound in pure
// Python:
//
//   * att_parallel_read  — multithreaded pread of tensor segments from a
//     checkpoint file straight into destination buffers (drives
//     serialization.load_flat_dict; checkpoint-load latency is a headline
//     benchmark: reference big_model_inference loads are 8.7-112s).
//   * att_parallel_memcpy — multithreaded scatter/gather copy used by the
//     prefetcher to assemble per-host batch buffers while the previous
//     step runs on device (ctypes releases the GIL around the call).
//   * att_ring_* — a slots/condvar ring buffer giving the double-buffered
//     producer/consumer contract (pallas_guide.md double-buffering pattern,
//     applied host-side).
//   * att_quantize_group — single-pass per-group weight quantization
//     (linear int8/int4 + NF4) straight from the checkpoint's bf16/fp32
//     bytes. Quantize-on-load halves/quarters the bytes crossing the
//     host->device link (the TTFT bottleneck); the numpy version costs
//     ~7 full passes over fp32 temporaries, this one reads the source once
//     and writes packed bytes + scales once.
//
// Pure C ABI on purpose: loaded via ctypes, no Python.h / pybind11
// dependency, trivially built with `g++ -O3 -shared -fPIC -pthread`.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Segment {
  uint64_t file_offset;
  uint64_t size;
  unsigned char *dst;
};

// Split [0, count) into contiguous chunks and run fn(chunk_begin, chunk_end)
// on num_threads workers.
void parallel_for(int count, int num_threads, void (*body)(int, void *), void *ctx) {
  if (num_threads < 1) num_threads = 1;
  if (num_threads > count) num_threads = count > 0 ? count : 1;
  std::atomic<int> next{0};
  std::vector<std::thread> workers;
  workers.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) {
    workers.emplace_back([&]() {
      int i;
      while ((i = next.fetch_add(1)) < count) body(i, ctx);
    });
  }
  for (auto &w : workers) w.join();
}

// NormalFloat4 code (QLoRA) — must match utils/quantization.NF4_CODE.
const float kNf4Code[16] = {
    -1.0f, -0.6961928009986877f, -0.5250730514526367f, -0.39491748809814453f,
    -0.28444138169288635f, -0.18477343022823334f, -0.09105003625154495f, 0.0f,
    0.07958029955625534f, 0.16093020141124725f, 0.24611230194568634f,
    0.33791524171829224f, 0.4407098591327667f, 0.5626170039176941f,
    0.7229568362236023f, 1.0f};

inline float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

inline int8_t nf4_index(float x) {
  // nearest code level; the code is sorted, 16 entries -> unrolled binary
  // search over midpoints
  int lo = 0, hi = 15;
  while (lo < hi) {
    int mid = (lo + hi) / 2;
    float boundary = 0.5f * (kNf4Code[mid] + kNf4Code[mid + 1]);
    if (x > boundary)
      lo = mid + 1;
    else
      hi = mid;
  }
  return static_cast<int8_t>(lo);
}

struct QuantCtx {
  const unsigned char *src;
  int src_dtype; // 0 = fp32, 1 = bf16
  uint64_t k, n, group;
  int bits;
  int mode; // 0 = linear, 1 = nf4
  int8_t *out_q;
  float *out_scale;
};

inline float load_src(const QuantCtx &c, uint64_t r, uint64_t j) {
  if (c.src_dtype == 0)
    return reinterpret_cast<const float *>(c.src)[r * c.n + j];
  return bf16_to_f32(reinterpret_cast<const uint16_t *>(c.src)[r * c.n + j]);
}

void quant_one_group(int g, void *vctx) {
  QuantCtx &c = *static_cast<QuantCtx *>(vctx);
  const uint64_t r0 = static_cast<uint64_t>(g) * c.group;
  const uint64_t r1 = r0 + c.group;
  const float qmax = c.bits == 8 ? 127.0f : 7.0f;
  // pass 1: per-column absmax over the group's rows
  std::vector<float> amax(c.n, 0.0f);
  for (uint64_t r = r0; r < r1; ++r)
    for (uint64_t j = 0; j < c.n; ++j) {
      float v = load_src(c, r, j);
      float a = v < 0 ? -v : v;
      if (a > amax[j]) amax[j] = a;
    }
  float *scale_row = c.out_scale + static_cast<uint64_t>(g) * c.n;
  for (uint64_t j = 0; j < c.n; ++j) {
    float s;
    if (c.mode == 1)
      s = amax[j] > 0 ? amax[j] : 1.0f; // nf4: normalize to [-1, 1]
    else
      s = amax[j] > 0 ? amax[j] / qmax : 1.0f;
    scale_row[j] = s;
  }
  // DIVISION, not reciprocal-multiply: bit-exact with the numpy fallback
  // (np.round(w/scale)) — a reciprocal flips values sitting on .5 ties
  const float *div = scale_row;
  // pass 2: quantize (source read once more — still resident in cache for
  // typical group x n tiles)
  if (c.bits == 8) {
    for (uint64_t r = r0; r < r1; ++r) {
      int8_t *out_row = c.out_q + r * c.n;
      for (uint64_t j = 0; j < c.n; ++j) {
        float v = load_src(c, r, j) / div[j];
        int iq = static_cast<int>(std::nearbyintf(v)); // half-even, like np.round
        if (iq > 127) iq = 127;
        if (iq < -127) iq = -127;
        out_row[j] = static_cast<int8_t>(iq);
      }
    }
    return;
  }
  // 4-bit: rows pack two-per-byte along dim 0 (row 2i -> low nibble,
  // row 2i+1 -> high nibble), exactly like the numpy packer. A group is
  // always a whole number of PACKED rows when group is even; with odd k
  // the final (pad) row is zero.
  for (uint64_t r = r0; r < r1; r += 2) {
    int8_t *out_row = c.out_q + (r / 2) * c.n;
    for (uint64_t j = 0; j < c.n; ++j) {
      int lo, hi;
      if (c.mode == 1) {
        lo = nf4_index(load_src(c, r, j) / div[j]);
        hi = (r + 1 < c.k) ? nf4_index(load_src(c, r + 1, j) / div[j]) : 0;
      } else {
        lo = static_cast<int>(std::nearbyintf(load_src(c, r, j) / div[j]));
        if (lo > 7) lo = 7;
        if (lo < -7) lo = -7;
        if (r + 1 < c.k) {
          hi = static_cast<int>(std::nearbyintf(load_src(c, r + 1, j) / div[j]));
          if (hi > 7) hi = 7;
          if (hi < -7) hi = -7;
        } else {
          hi = 0;
        }
      }
      out_row[j] = static_cast<int8_t>((lo & 0x0F) | ((hi & 0x0F) << 4));
    }
  }
}

} // namespace

extern "C" {

// Per-group symmetric quantization of a row-major [k, n] matrix along dim 0.
// src_dtype: 0 = fp32, 1 = bf16 (uint16 storage). mode: 0 = linear int
// (scale = amax/qmax), 1 = nf4 (scale = amax, output = codebook indices).
// bits 8: out_q is int8 [k, n]. bits 4: out_q is packed [(k+1)/2, n], two
// rows per byte (low nibble = even row). out_scale: fp32 [k/group, n].
// `group` must divide k and, for bits=4 with k > group, be even.
// Returns 0 on success.
int att_quantize_group(const unsigned char *src, int src_dtype, uint64_t k,
                       uint64_t n, uint64_t group, int bits, int mode,
                       int8_t *out_q, float *out_scale, int num_threads) {
  if (k == 0 || n == 0 || group == 0 || k % group != 0) return -1;
  if (bits != 8 && bits != 4) return -2;
  if (bits == 4 && group % 2 != 0 && k != group) return -3;
  QuantCtx ctx{src, src_dtype, k, n, group, bits, mode, out_q, out_scale};
  int groups = static_cast<int>(k / group);
  parallel_for(groups, num_threads, quant_one_group, &ctx);
  return 0;
}

// Read `count` segments of `path` into caller-provided buffers.
// Returns 0 on success, -errno-style negative on failure.
int att_parallel_read(const char *path, const uint64_t *file_offsets,
                      const uint64_t *sizes, unsigned char **dsts, int count,
                      int num_threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  std::atomic<int> err{0};
  struct Ctx {
    int fd;
    const uint64_t *off;
    const uint64_t *sz;
    unsigned char **dst;
    std::atomic<int> *err;
  } ctx{fd, file_offsets, sizes, dsts, &err};
  parallel_for(
      count, num_threads,
      [](int i, void *p) {
        auto *c = static_cast<Ctx *>(p);
        uint64_t remaining = c->sz[i];
        uint64_t off = c->off[i];
        unsigned char *dst = c->dst[i];
        while (remaining > 0) {
          ssize_t got = ::pread(c->fd, dst, remaining, (off_t)off);
          if (got <= 0) {
            c->err->store(-2);
            return;
          }
          remaining -= (uint64_t)got;
          off += (uint64_t)got;
          dst += got;
        }
      },
      &ctx);
  ::close(fd);
  return err.load();
}

void att_parallel_memcpy(unsigned char **dsts, const unsigned char **srcs,
                         const uint64_t *sizes, int count, int num_threads) {
  struct Ctx {
    unsigned char **dst;
    const unsigned char **src;
    const uint64_t *sz;
  } ctx{dsts, srcs, sizes};
  parallel_for(
      count, num_threads,
      [](int i, void *p) {
        auto *c = static_cast<Ctx *>(p);
        std::memcpy(c->dst[i], c->src[i], c->sz[i]);
      },
      &ctx);
}

// ---------------------------------------------------------------------------
// Ring buffer: fixed slot count, each slot a contiguous byte buffer.
// Producer: acquire_fill -> write via slot_ptr -> commit_fill.
// Consumer: acquire_read -> read -> release_read.
// ---------------------------------------------------------------------------

struct Ring {
  int slots;
  uint64_t slot_bytes;
  std::vector<std::vector<unsigned char>> storage;
  std::vector<int> state; // 0=free, 1=filling, 2=ready, 3=reading
  int fill_cursor = 0;
  int read_cursor = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable cv;
};

void *att_ring_create(int slots, uint64_t slot_bytes) {
  auto *r = new Ring();
  r->slots = slots;
  r->slot_bytes = slot_bytes;
  r->storage.resize(slots);
  for (auto &s : r->storage) s.resize(slot_bytes);
  r->state.assign(slots, 0);
  return r;
}

void att_ring_destroy(void *ring) { delete static_cast<Ring *>(ring); }

void att_ring_close(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->cv.notify_all();
}

// Returns slot index, or -1 if the ring is closed.
int att_ring_acquire_fill(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  std::unique_lock<std::mutex> lk(r->mu);
  int slot = r->fill_cursor;
  r->cv.wait(lk, [&] { return r->closed || r->state[slot] == 0; });
  if (r->closed) return -1;
  r->state[slot] = 1;
  r->fill_cursor = (slot + 1) % r->slots;
  return slot;
}

void att_ring_commit_fill(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->state[slot] = 2;
  }
  r->cv.notify_all();
}

int att_ring_acquire_read(void *ring) {
  auto *r = static_cast<Ring *>(ring);
  std::unique_lock<std::mutex> lk(r->mu);
  int slot = r->read_cursor;
  r->cv.wait(lk, [&] { return r->closed || r->state[slot] == 2; });
  if (r->state[slot] != 2) return -1; // closed and nothing ready
  r->state[slot] = 3;
  r->read_cursor = (slot + 1) % r->slots;
  return slot;
}

void att_ring_release_read(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->state[slot] = 0;
  }
  r->cv.notify_all();
}

unsigned char *att_ring_slot_ptr(void *ring, int slot) {
  auto *r = static_cast<Ring *>(ring);
  return r->storage[slot].data();
}

uint64_t att_ring_slot_bytes(void *ring) {
  return static_cast<Ring *>(ring)->slot_bytes;
}

} // extern "C"
