"""Complete CV example: the base cv_example plus checkpointing, mid-epoch
resume, LR scheduling, and experiment tracking.

Mirrors the user-API shape of the reference
(/root/reference/examples/complete_cv_example.py:110-280): --with_tracking
enables init_trackers/log/end_training, --checkpointing_steps {N,"epoch"}
drives save_state into project_dir, --resume_from_checkpoint restores state
(including BatchNorm running statistics, which travel as extra mutable
state through the checkpoint) and skips already-seen batches via
skip_first_batches.
"""

from __future__ import annotations

import argparse
import os

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.data import skip_first_batches
from accelerate_tpu.models import ResNet, VisionConfig
from accelerate_tpu.utils.random import set_seed

import sys

sys.path.append(os.path.dirname(os.path.abspath(__file__)))
from cv_example import PrototypeImageDataset  # noqa: E402


def training_function(config, args):
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="jsonl" if args.with_tracking else None,
        project_dir=args.project_dir,
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config)

    lr, num_epochs, seed, batch_size = (
        config["lr"], int(config["num_epochs"]), int(config["seed"]), int(config["batch_size"])
    )
    image_size = int(config["image_size"])
    set_seed(seed)
    model_config = (
        VisionConfig.tiny(image_size=image_size)
        if (args.cpu or args.tiny)
        else VisionConfig.resnet50(num_classes=config["num_classes"], image_size=image_size)
    )

    train_ds = PrototypeImageDataset(config["train_len"], image_size, config["num_classes"], seed=seed)
    eval_ds = PrototypeImageDataset(config["eval_len"], image_size, config["num_classes"], seed=seed + 1)
    train_dataloader = DataLoader(train_ds, batch_size=batch_size, shuffle=True, drop_last=True)
    eval_dataloader = DataLoader(eval_ds, batch_size=batch_size, shuffle=False)

    model_def = ResNet(model_config)
    variables = model_def.init_variables(jax.random.PRNGKey(seed), batch_size=batch_size, image_size=image_size)
    total_steps = len(train_dataloader) * num_epochs
    lr_schedule = optax.cosine_decay_schedule(lr, max(total_steps, 1))
    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        Model(model_def, variables),
        optax.sgd(lr_schedule, momentum=0.9),
        train_dataloader,
        eval_dataloader,
        lr_schedule,
    )

    overall_step = 0
    starting_epoch = 0
    resume_step = None
    if args.resume_from_checkpoint:
        accelerator.print(f"Resuming from checkpoint: {args.resume_from_checkpoint}")
        accelerator.load_state(args.resume_from_checkpoint)
        path = os.path.basename(args.resume_from_checkpoint.rstrip("/"))
        if "epoch" in path:
            starting_epoch = int(path.replace("epoch_", "")) + 1
        else:
            resume_step = int(path.replace("step_", ""))
            starting_epoch = resume_step // len(train_dataloader)
            resume_step -= starting_epoch * len(train_dataloader)
            overall_step = resume_step + starting_epoch * len(train_dataloader)

    for epoch in range(starting_epoch, num_epochs):
        model.train()
        total_loss = 0.0
        if args.resume_from_checkpoint and epoch == starting_epoch and resume_step is not None:
            active_dataloader = skip_first_batches(train_dataloader, resume_step)
        else:
            active_dataloader = train_dataloader
        for batch in active_dataloader:
            outputs = model(batch["image"], labels=batch["label"], train=True)
            total_loss += float(jax.device_get(outputs["loss"]))
            accelerator.backward(outputs["loss"])
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
            overall_step += 1

            if isinstance(args.checkpointing_steps, int) and overall_step % args.checkpointing_steps == 0:
                accelerator.save_state(os.path.join(args.project_dir or ".", f"step_{overall_step}"))

        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            outputs = model(batch["image"])
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["label"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accuracy = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy = {100 * accuracy:.2f}%")
        if args.with_tracking:
            accelerator.log(
                {"accuracy": accuracy, "train_loss": total_loss / max(len(train_dataloader), 1)},
                step=epoch,
            )
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.project_dir or ".", f"epoch_{epoch}"))

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Complete CV training script example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    parser.add_argument(
        "--checkpointing_steps", type=str, default=None,
        help="Save state every N steps (int) or 'epoch'.",
    )
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default=None)
    args = parser.parse_args()
    if args.checkpointing_steps is not None and args.checkpointing_steps != "epoch":
        args.checkpointing_steps = int(args.checkpointing_steps)
    config = {"lr": 0.02, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16,
              "image_size": 224, "num_classes": 37, "train_len": 512, "eval_len": 128}
    if args.tiny or args.cpu:
        config.update({"image_size": 32, "num_classes": 8, "train_len": 128, "eval_len": 64, "batch_size": 8})
    training_function(config, args)


if __name__ == "__main__":
    main()
