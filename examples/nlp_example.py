"""Canonical NLP fine-tuning example: BERT-base on an MRPC-shaped paraphrase
task.

Mirrors the user-API shape of the reference's flagship example
(/root/reference/examples/nlp_example.py:47-205): get_dataloaders ->
training_function(config, args) with Accelerator() -> prepare(model,
optimizer, loaders, scheduler) -> imperative train loop with
accelerator.backward / optimizer.step / scheduler.step -> eval loop with
gather_for_metrics. The same script runs single-chip, multi-host (under
`accelerate-tpu launch`), and on the CPU simulator (--cpu).

Data is synthetic but MRPC-shaped (sentence pairs, [CLS] a [SEP] b [SEP]
packing, token-type segments, padding mask, binary paraphrase label with a
token-overlap signal) — this image has no network egress, and the example's
job is to demonstrate the training contract, not to download GLUE.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

MAX_CHIP_BATCH_SIZE = 16
EVAL_BATCH_SIZE = 32
CLS, SEP, PAD = 1, 2, 0


class ParaphraseDataset:
    """MRPC-shaped synthetic pairs. Label 1 pairs share most content tokens
    (a shuffled, lightly corrupted copy); label 0 pairs are independent."""

    def __init__(self, length: int, seq_len: int, vocab_size: int, seed: int):
        rng = np.random.default_rng(seed)
        half = seq_len // 2 - 2
        self.examples = []
        for _ in range(length):
            label = int(rng.integers(0, 2))
            a = rng.integers(3, vocab_size, size=half)
            if label:
                b = a.copy()
                rng.shuffle(b)
                flip = rng.random(half) < 0.1
                b[flip] = rng.integers(3, vocab_size, size=int(flip.sum()))
            else:
                b = rng.integers(3, vocab_size, size=half)
            la = int(rng.integers(half // 2, half + 1))
            lb = int(rng.integers(half // 2, half + 1))
            ids = np.full(seq_len, PAD, np.int32)
            types = np.zeros(seq_len, np.int32)
            ids[0] = CLS
            ids[1 : 1 + la] = a[:la]
            ids[1 + la] = SEP
            ids[2 + la : 2 + la + lb] = b[:lb]
            types[2 + la : 3 + la + lb] = 1
            ids[2 + la + lb] = SEP
            mask = (ids != PAD).astype(np.int32)
            self.examples.append(
                {"input_ids": ids, "attention_mask": mask, "token_type_ids": types, "labels": label}
            )

    def __len__(self):
        return len(self.examples)

    def __getitem__(self, i):
        return self.examples[i]


def get_dataloaders(accelerator: Accelerator, batch_size: int, model_config: EncoderConfig,
                    train_len: int = 512, eval_len: int = 128):
    """Create train/eval DataLoaders (reference get_dataloaders:47). Padding
    to a fixed seq_len up front — on TPU, static shapes are what keep the
    whole epoch on one compiled program."""
    seq_len = min(model_config.max_seq_len, 128)
    with accelerator.main_process_first():
        train_ds = ParaphraseDataset(train_len, seq_len, model_config.vocab_size, seed=42)
        eval_ds = ParaphraseDataset(eval_len, seq_len, model_config.vocab_size, seed=43)
    train_dataloader = DataLoader(train_ds, batch_size=batch_size, shuffle=True, drop_last=True)
    eval_dataloader = DataLoader(eval_ds, batch_size=EVAL_BATCH_SIZE, shuffle=False)
    return train_dataloader, eval_dataloader


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    # If the requested batch exceeds one chip's comfort zone, fall back to
    # gradient accumulation (reference nlp_example.py:124-128)
    gradient_accumulation_steps = 1
    if batch_size > MAX_CHIP_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_CHIP_BATCH_SIZE
        batch_size = MAX_CHIP_BATCH_SIZE

    set_seed(seed)
    model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )

    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    total_steps = (len(train_dataloader) * num_epochs) // gradient_accumulation_steps
    warmup = min(100, max(total_steps // 10, 1))
    lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr_schedule), train_dataloader, eval_dataloader, lr_schedule
    )

    for epoch in range(num_epochs):
        model.train()
        for step, batch in enumerate(train_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
                deterministic=False,
            )
            loss = outputs["loss"]
            accelerator.backward(loss)
            if step % gradient_accumulation_steps == 0:
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for step, batch in enumerate(eval_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Simple example of a training script.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
