"""Seq2seq (T5-family) fine-tuning example: sequence reversal as a stand-in
translation task.

The reference's T5 path lives behind its Megatron integration
(/root/reference/src/accelerate/utils/megatron_lm.py:720-877 T5TrainStep);
this example shows the same user contract on the TPU-native stack:
Accelerator() -> prepare(model, optimizer, loaders, scheduler) -> train loop
with accelerator.backward -> eval with cached seq2seq generation +
gather_for_metrics.

Data is synthetic (reverse the source token sequence) — the point is the
encoder-decoder training + generation contract, not a real corpus: reversal
is impossible without cross-attention, so eval accuracy directly measures
the seq2seq machinery working.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.generation import generate_seq2seq
from accelerate_tpu.models import Seq2SeqConfig, Seq2SeqLM
from accelerate_tpu.utils.random import set_seed

PAD = 0


class ReversalDataset:
    """source: random tokens (+ padding); target: the sequence reversed."""

    def __init__(self, length: int, seq_len: int, vocab_size: int, seed: int):
        rng = np.random.default_rng(seed)
        self.examples = []
        for _ in range(length):
            n = int(rng.integers(seq_len // 2, seq_len + 1))
            toks = rng.integers(3, vocab_size, size=n)
            src = np.full(seq_len, PAD, np.int32)
            src[:n] = toks
            tgt = np.full(seq_len, -100, np.int32)  # -100 = ignored positions
            tgt[:n] = toks[::-1]
            mask = (src != PAD).astype(np.int32)
            self.examples.append(
                {"input_ids": src, "attention_mask": mask, "labels": tgt}
            )

    def __len__(self):
        return len(self.examples)

    def __getitem__(self, i):
        return self.examples[i]


def get_dataloaders(accelerator: Accelerator, batch_size: int, cfg: Seq2SeqConfig,
                    train_len: int = 512, eval_len: int = 64):
    seq_len = min(cfg.max_seq_len, 16)
    with accelerator.main_process_first():
        train_ds = ReversalDataset(train_len, seq_len, cfg.vocab_size, seed=42)
        eval_ds = ReversalDataset(eval_len, seq_len, cfg.vocab_size, seed=43)
    train = DataLoader(train_ds, batch_size=batch_size, shuffle=True, drop_last=True)
    eval_ = DataLoader(eval_ds, batch_size=batch_size, shuffle=False)
    return train, eval_


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(config["seed"])

    cfg = Seq2SeqConfig.tiny(num_layers=2, max_cache_len=32) if (args.cpu or args.tiny) else Seq2SeqConfig(
        vocab_size=32_128, num_layers=6, embed_dim=512, num_heads=8, max_seq_len=512,
        max_target_len=512,
    )
    model_def = Seq2SeqLM(cfg, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(config["seed"]), batch_size=config["batch_size"],
        seq_len=min(cfg.max_seq_len, 16), target_len=min(cfg.max_target_len, 16),
    )
    train_dl, eval_dl = get_dataloaders(
        accelerator, config["batch_size"], cfg,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 64),
    )
    total = len(train_dl) * config["num_epochs"]
    schedule = optax.warmup_cosine_decay_schedule(0.0, config["lr"], min(20, total // 10 + 1), max(total, 2))

    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        Model(model_def, variables), optax.adamw(schedule), train_dl, eval_dl, schedule
    )

    for epoch in range(config["num_epochs"]):
        model.train()
        for batch in train_dl:
            outputs = model(
                batch["input_ids"],
                labels=batch["labels"],
                attention_mask=batch["attention_mask"],
                deterministic=False,
            )
            accelerator.backward(outputs["loss"])
            optimizer.step()
            scheduler.step()
            optimizer.zero_grad()

        # eval: greedy cached generation, exact-sequence accuracy on the
        # non-ignored positions
        model.eval()
        unwrapped = model.unwrap()
        correct = total_n = 0
        for batch in eval_dl:
            gen = generate_seq2seq(
                model_def, unwrapped.params,
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                max_new_tokens=int(batch["labels"].shape[1]),
            )
            gen, labels = accelerator.gather_for_metrics((gen, batch["labels"]))
            gen, labels = np.asarray(gen), np.asarray(labels)
            valid = labels != -100
            correct += int(((gen == labels) | ~valid).all(axis=1).sum())
            total_n += labels.shape[0]
        accelerator.print(
            f"epoch {epoch}: {{'reversal_accuracy': {correct / max(total_n, 1):.4f}}}"
        )

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Seq2seq (T5-family) training example.")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    config = {"lr": 1e-3, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 32})
    training_function(config, args)


if __name__ == "__main__":
    main()
