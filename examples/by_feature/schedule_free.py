"""By-feature example: schedule-free training.

Mirrors the reference feature example
(/root/reference/examples/by_feature/schedule_free.py): train with a
schedule-free optimizer (Defazio et al. 2024) — no LR schedule, no horizon
hyperparameter, and the `lr_scheduler.step()` line disappears from the
loop. On the optax side this is `optax.contrib.schedule_free` wrapping a
base optimizer; the one behavioral subtlety is that evaluation should use
the averaged (x) parameters, obtained with
`optax.contrib.schedule_free_eval_params(opt_state, params)`.

Diff this file against examples/nlp_example.py: the `# New Code #` fences
contain the entire feature.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

MAX_CHIP_BATCH_SIZE = 16


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    # If the requested batch exceeds one chip's comfort zone, fall back to
    # gradient accumulation (reference nlp_example.py:124-128)
    gradient_accumulation_steps = 1
    if batch_size > MAX_CHIP_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_CHIP_BATCH_SIZE
        batch_size = MAX_CHIP_BATCH_SIZE

    set_seed(seed)
    model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )

    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )

    # New Code #
    # no warmup_cosine_decay_schedule, no total-steps arithmetic: the
    # schedule-free wrapper replaces the entire LR schedule
    optimizer_def = optax.contrib.schedule_free(
        optax.adamw(lr), learning_rate=lr, b1=0.9
    )
    model, optimizer, train_dataloader, eval_dataloader = accelerator.prepare(
        Model(model_def, variables), optimizer_def, train_dataloader, eval_dataloader
    )
    # End New Code #

    for epoch in range(num_epochs):
        model.train()
        for step, batch in enumerate(train_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
                deterministic=False,
            )
            loss = outputs["loss"]
            accelerator.backward(loss)
            if step % gradient_accumulation_steps == 0:
                # New Code #
                # no lr_scheduler.step(): schedule-free has no schedule
                optimizer.step()
                optimizer.zero_grad()
                # End New Code #

        model.eval()
        # New Code #
        # evaluate on the schedule-free AVERAGED params (x), not the fast
        # iterate (y/z) the optimizer trains on
        train_params = model._engine.params
        model._engine.params = optax.contrib.schedule_free_eval_params(
            model._engine.opt_state, train_params
        )
        # End New Code #
        correct = total = 0
        for step, batch in enumerate(eval_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")
        # New Code #
        model._engine.params = train_params  # restore the fast iterate
        # End New Code #

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Schedule-free optimizer example.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    # New Code #
    # schedule-free runs hotter than scheduled AdamW; 1e-3-ish works where
    # a cosine schedule would have peaked around the same value
    config = {"lr": 1e-3, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    # End New Code #
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
