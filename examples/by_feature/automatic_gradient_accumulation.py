"""By-feature example: automatic gradient accumulation.

Mirrors the reference feature example
(/root/reference/examples/by_feature/automatic_gradient_accumulation.py):
combine `find_executable_batch_size` with gradient accumulation so the
script adapts to whatever HBM the chip has. Start from the OBSERVED batch
size the user wants; if the step OOMs, the decorator halves the per-chip
batch and raises the accumulation count to keep the effective batch — and
therefore the training math — identical.

Diff this file against examples/nlp_example.py: the `# New Code #` fences
contain the entire feature.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# New Code #
from accelerate_tpu.utils.memory import find_executable_batch_size
# End New Code #

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

MAX_CHIP_BATCH_SIZE = 16


def training_function(config, args):
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    # New Code #
    # the batch the user WANTS (the observed/effective batch)
    observed_batch_size = int(config["batch_size"])

    @find_executable_batch_size(starting_batch_size=observed_batch_size)
    def inner_training_loop(batch_size):
        # everything rebuilt per attempt: a halved batch means a fresh
        # Accelerator with the matching accumulation count
        accumulation = max(1, observed_batch_size // batch_size)
        accelerator = Accelerator(
            mixed_precision=args.mixed_precision,
            gradient_accumulation_steps=accumulation,
        )
        accelerator.print(f"trying per-chip batch {batch_size} x accum {accumulation}")
        # End New Code #

        set_seed(seed)
        model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()
        train_dataloader, eval_dataloader = get_dataloaders(
            accelerator, batch_size, model_config,
            train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
        )

        model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
        variables = model_def.init_variables(
            jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
        )
        # New Code #
        total_steps = (len(train_dataloader) * num_epochs) // accumulation
        # End New Code #
        warmup = min(100, max(total_steps // 10, 1))
        lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
            Model(model_def, variables), optax.adamw(lr_schedule), train_dataloader, eval_dataloader, lr_schedule
        )

        for epoch in range(num_epochs):
            model.train()
            for step, batch in enumerate(train_dataloader):
                # New Code #
                # accumulate() gates the optimizer step + grad sync to fire
                # once per effective batch, whatever per-chip size survived
                with accelerator.accumulate(model):
                    # End New Code #
                    outputs = model(
                        batch["input_ids"],
                        attention_mask=batch["attention_mask"],
                        token_type_ids=batch["token_type_ids"],
                        labels=batch["labels"],
                        deterministic=False,
                    )
                    loss = outputs["loss"]
                    accelerator.backward(loss)
                    # New Code #
                    # no manual `if step % accumulation` gate: the
                    # accumulate() context above owns the step cadence
                    optimizer.step()
                    lr_scheduler.step()
                    optimizer.zero_grad()
                    # End New Code #

            model.eval()
            correct = total = 0
            for step, batch in enumerate(eval_dataloader):
                outputs = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                )
                predictions = outputs["logits"].argmax(axis=-1)
                predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
                correct += int((np.asarray(predictions) == np.asarray(references)).sum())
                total += int(np.asarray(references).shape[0])
            accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

        accelerator.end_training()
        # New Code #

    inner_training_loop()
    # End New Code #


def main():
    parser = argparse.ArgumentParser(description="Automatic gradient accumulation example.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
