"""By-feature example: experiment tracking.

Mirrors the reference feature example (/root/reference/examples/by_feature/
tracking.py): `Accelerator(log_with=...)` + `init_trackers` / `log` /
`end_training`. The jsonl tracker used here needs no external service; swap
`log_with="wandb"` (or tensorboard/mlflow/comet/aim/clearml/dvclive) when
those are installed.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


def training_function(config, args):
    # New for this feature: pick a tracker and a project dir
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with=args.log_with,
        project_dir=args.project_dir,
    )
    accelerator.init_trackers("tracking_example", config)

    lr, num_epochs, seed, batch_size = (
        config["lr"], int(config["num_epochs"]), int(config["seed"]), int(config["batch_size"])
    )
    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )
    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    model, optimizer, train_dataloader, eval_dataloader = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr), train_dataloader, eval_dataloader
    )

    overall_step = 0
    for epoch in range(num_epochs):
        model.train()
        total_loss = 0.0
        for batch in train_dataloader:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"], labels=batch["labels"],
                deterministic=False,
            )
            loss = float(jax.device_get(outputs["loss"]))
            total_loss += loss
            accelerator.backward(outputs["loss"])
            optimizer.step()
            optimizer.zero_grad()
            overall_step += 1
            accelerator.log({"train_loss": loss}, step=overall_step)

        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accuracy = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: {{'accuracy': {accuracy:.4f}}}")
        accelerator.log(
            {"accuracy": accuracy, "epoch_loss": total_loss / max(len(train_dataloader), 1)},
            step=overall_step,
        )

    accelerator.end_training()  # flushes/closes every tracker


def main():
    parser = argparse.ArgumentParser(description="Tracking feature example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    parser.add_argument("--log_with", type=str, default="jsonl")
    parser.add_argument("--project_dir", type=str, default="tracking_logs")
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
