"""By-feature example: k-fold cross validation.

Mirrors the reference feature example
(/root/reference/examples/by_feature/cross_validation.py): train k models
on k train/validation splits, evaluate each on the SAME held-out test set,
and average the per-fold test predictions into an ensemble metric. The
distributed care points: every process must build identical folds (seeded
split before sharding), and per-fold metrics must be gathered with
`gather_for_metrics` so the ensemble math sees full, dedup'd arrays.

Diff this file against examples/nlp_example.py: the `# New Code #` fences
contain the entire feature.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import EVAL_BATCH_SIZE, ParaphraseDataset  # noqa: E402

MAX_CHIP_BATCH_SIZE = 16


# New Code #
class _Subset:
    def __init__(self, ds, idx):
        self.ds, self.idx = ds, list(idx)

    def __len__(self):
        return len(self.idx)

    def __getitem__(self, i):
        return self.ds[self.idx[i]]


def get_fold_dataloaders(accelerator, batch_size, model_config, fold, num_folds,
                         train_len=512, test_len=128):
    """Identical seeded folds on every process: fold f validates on slice f
    of the training pool and trains on the rest; the test set is shared."""
    seq_len = min(model_config.max_seq_len, 128)
    with accelerator.main_process_first():
        pool = ParaphraseDataset(train_len, seq_len, model_config.vocab_size, seed=42)
        test_ds = ParaphraseDataset(test_len, seq_len, model_config.vocab_size, seed=43)
    perm = np.random.RandomState(0).permutation(train_len)
    folds = np.array_split(perm, num_folds)
    valid_idx = folds[fold]
    train_idx = np.concatenate([f for i, f in enumerate(folds) if i != fold])
    train_dataloader = DataLoader(_Subset(pool, train_idx), batch_size=batch_size,
                                  shuffle=True, drop_last=True)
    valid_dataloader = DataLoader(_Subset(pool, valid_idx), batch_size=EVAL_BATCH_SIZE)
    test_dataloader = DataLoader(test_ds, batch_size=EVAL_BATCH_SIZE)
    return train_dataloader, valid_dataloader, test_dataloader
# End New Code #


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    # If the requested batch exceeds one chip's comfort zone, fall back to
    # gradient accumulation (reference nlp_example.py:124-128)
    gradient_accumulation_steps = 1
    if batch_size > MAX_CHIP_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_CHIP_BATCH_SIZE
        batch_size = MAX_CHIP_BATCH_SIZE

    set_seed(seed)
    model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()

    # New Code #
    num_folds = int(args.num_folds)
    test_len = config.get("eval_len", 128)
    test_logit_sum = None
    test_references = None
    for fold in range(num_folds):
        train_dataloader, valid_dataloader, test_dataloader = get_fold_dataloaders(
            accelerator, batch_size, model_config, fold, num_folds,
            train_len=config.get("train_len", 512), test_len=test_len,
        )
        # End New Code #

        model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
        variables = model_def.init_variables(
            jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
        )
        total_steps = (len(train_dataloader) * num_epochs) // gradient_accumulation_steps
        warmup = min(100, max(total_steps // 10, 1))
        lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

        # New Code #
        model, optimizer, train_dataloader, valid_dataloader, test_dataloader, lr_scheduler = (
            accelerator.prepare(
                Model(model_def, variables), optax.adamw(lr_schedule),
                train_dataloader, valid_dataloader, test_dataloader, lr_schedule,
            )
        )
        # End New Code #

        for epoch in range(num_epochs):
            model.train()
            for step, batch in enumerate(train_dataloader):
                outputs = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    labels=batch["labels"],
                    deterministic=False,
                )
                loss = outputs["loss"]
                accelerator.backward(loss)
                if step % gradient_accumulation_steps == 0:
                    optimizer.step()
                    lr_scheduler.step()
                    optimizer.zero_grad()

            model.eval()
            correct = total = 0
            # New Code #
            for step, batch in enumerate(valid_dataloader):
                # End New Code #
                outputs = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                )
                predictions = outputs["logits"].argmax(axis=-1)
                predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
                correct += int((np.asarray(predictions) == np.asarray(references)).sum())
                total += int(np.asarray(references).shape[0])
            # New Code #
            accelerator.print(f"fold {fold} epoch {epoch}: "
                              f"{{'valid_accuracy': {correct / max(total, 1):.4f}}}")

        # this fold's vote on the shared test set
        fold_logits, fold_refs = [], []
        for batch in test_dataloader:
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            logits, references = accelerator.gather_for_metrics(
                (outputs["logits"], batch["labels"])
            )
            fold_logits.append(np.asarray(logits, np.float32))
            fold_refs.append(np.asarray(references))
        logits = np.concatenate(fold_logits)
        if test_logit_sum is None:
            test_logit_sum = logits
            test_references = np.concatenate(fold_refs)
        else:
            test_logit_sum = test_logit_sum + logits

    ensemble = test_logit_sum.argmax(axis=-1)
    accuracy = float((ensemble == test_references).mean())
    accelerator.print(f"{num_folds}-fold ensemble test accuracy: {accuracy:.4f} "
                      f"on {test_references.shape[0]} examples")
    # End New Code #

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="k-fold cross-validation example.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    # New Code #
    parser.add_argument("--num_folds", type=int, default=3, help="Number of CV folds.")
    # End New Code #
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 2, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
