"""By-feature example: correct metrics across processes.

Mirrors the reference feature example
(/root/reference/examples/by_feature/multi_process_metrics.py): when eval
runs data-parallel, each process only sees its shard, and the LAST batch of
an epoch may contain wraparound duplicates added to keep batches even.
`accelerator.gather_for_metrics(...)` gathers every process's predictions
AND drops those duplicates, so the metric denominator is exactly
`len(eval_set)` — naive `gather` would overcount.

Diff this file against examples/nlp_example.py: the `# New Code #` fences
contain the entire feature.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

MAX_CHIP_BATCH_SIZE = 16


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    # If the requested batch exceeds one chip's comfort zone, fall back to
    # gradient accumulation (reference nlp_example.py:124-128)
    gradient_accumulation_steps = 1
    if batch_size > MAX_CHIP_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_CHIP_BATCH_SIZE
        batch_size = MAX_CHIP_BATCH_SIZE

    set_seed(seed)
    model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )

    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    total_steps = (len(train_dataloader) * num_epochs) // gradient_accumulation_steps
    warmup = min(100, max(total_steps // 10, 1))
    lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr_schedule), train_dataloader, eval_dataloader, lr_schedule
    )

    for epoch in range(num_epochs):
        model.train()
        for step, batch in enumerate(train_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
                deterministic=False,
            )
            loss = outputs["loss"]
            accelerator.backward(loss)
            if step % gradient_accumulation_steps == 0:
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        model.eval()
        # New Code #
        # accumulate per-batch arrays, gather once per batch; the dedup of
        # the ragged last batch happens inside gather_for_metrics, driven by
        # the dataloader's remainder bookkeeping
        all_predictions, all_references = [], []
        # End New Code #
        for step, batch in enumerate(eval_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            # New Code #
            all_predictions.append(np.asarray(predictions))
            all_references.append(np.asarray(references))
        predictions = np.concatenate(all_predictions)
        references = np.concatenate(all_references)
        # the denominator proves the dedup: exactly the eval set size, on
        # every process, no matter how ragged the final batch was
        assert references.shape[0] == config.get("eval_len", 64), references.shape
        accuracy = float((predictions == references).mean())
        accelerator.print(f"epoch {epoch}: {{'accuracy': {accuracy:.4f}, "
                          f"'examples': {references.shape[0]}}}")
        # End New Code #

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Multi-process metrics example.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
