"""By-feature example: pipeline-parallel training (GPipe / 1F1B).

The reference's pipeline-training story is its Megatron passthrough
(/root/reference/src/accelerate/utils/megatron_lm.py:926-1033 microbatch
schedules); here the same capability is two config knobs on the model and
one mesh axis:

- ``ShardingConfig(pipeline_parallel=S)`` puts a "stage" axis in the mesh;
- ``DecoderConfig(pipeline_stages=S, pipeline_schedule="gpipe"|"1f1b")``
  splits the layer stack into S stage groups and picks how the schedule
  trains: ``"gpipe"`` runs the forward belt under reverse-mode AD (simple,
  O(M) activation stash per stage), ``"1f1b"`` interleaves each
  microbatch's backward into the same scan (O(S) stash independent of M —
  more microbatches amortize the bubble at constant activation memory).

The training loop below is IDENTICAL for both schedules — the engine
detects the model-owned 1F1B backward automatically. Run with
``--schedule 1f1b`` / ``--schedule gpipe`` to compare.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model, ShardingConfig
from accelerate_tpu.models import DecoderConfig, DecoderLM
from accelerate_tpu.utils.random import set_seed


class CopyTaskDataset:
    """Language-model toy data: the second half of each row repeats the
    first half, so a causal LM can reach low loss only by actually
    attending — loss decrease measures real training."""

    def __init__(self, length: int, seq_len: int, vocab_size: int, seed: int):
        rng = np.random.default_rng(seed)
        half = seq_len // 2
        self.rows = []
        for _ in range(length):
            a = rng.integers(3, vocab_size, size=half)
            row = np.concatenate([a, a]).astype(np.int32)
            self.rows.append({"input_ids": row, "labels": row})

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]


def training_function(config, args):
    # New Code #
    # a "stage" mesh axis; data parallelism absorbs the rest of the chips
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        sharding_config=ShardingConfig(pipeline_parallel=2, data_parallel=-1),
    )
    set_seed(config["seed"])

    # New Code #
    cfg = DecoderConfig.tiny(
        num_layers=4,
        max_seq_len=config["seq_len"],
        pipeline_stages=2,
        pipeline_microbatches=config["microbatches"],
        pipeline_schedule=args.schedule,
    )
    model_def = DecoderLM(cfg, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(config["seed"]),
        batch_size=config["batch_size"],
        seq_len=config["seq_len"],
    )

    train_loader = DataLoader(
        CopyTaskDataset(config["train_len"], config["seq_len"], cfg.vocab_size, 0),
        batch_size=config["batch_size"],
        shuffle=True,
        drop_last=True,
    )
    model, optimizer, train_loader = accelerator.prepare(
        Model(model_def, variables), optax.adamw(config["lr"]), train_loader
    )
    step = accelerator.build_train_step()

    first = last = None
    for epoch in range(config["num_epochs"]):
        for batch in train_loader:
            metrics = step(batch)
            last = float(jax.device_get(metrics["loss"]))
            if first is None:
                first = last
        accelerator.print(
            f"epoch {epoch} [{args.schedule}]: loss {last:.4f}"
        )
    assert np.isfinite(last), last
    if config["num_epochs"] >= 2:
        # one tiny epoch is too noisy for a hard decrease assert (CI runs
        # --num_epochs 1); the default two epochs must actually train
        assert last < first, (first, last)
    accelerator.print(
        f"{{'schedule': '{args.schedule}', 'first_loss': {first:.4f}, "
        f"'final_loss': {last:.4f}}}"
    )
    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Pipeline-parallel training example.")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16"])
    parser.add_argument("--schedule", type=str, default="1f1b",
                        choices=["gpipe", "1f1b"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    if args.cpu:
        # env JAX_PLATFORMS=cpu is not enough on hosts whose sitecustomize
        # force-registers a TPU platform; set it before backend init
        jax.config.update("jax_platforms", "cpu")
    config = {
        "lr": 2e-3, "num_epochs": args.num_epochs or 2, "seed": 42,
        "batch_size": 8, "seq_len": 32, "microbatches": 4, "train_len": 64,
    }
    training_function(config, args)


if __name__ == "__main__":
    main()
