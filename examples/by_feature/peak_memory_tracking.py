"""By-feature example: peak device-memory tracking during training.

Analog of the reference feature example
(/root/reference/examples/by_feature/fsdp_with_peak_mem_tracking.py): train
under an FSDP-sharded mesh and report how much accelerator memory the step
actually uses. The torch version samples cuda max_memory_allocated; here
the numbers come from ``device.memory_stats()`` (peak_bytes_in_use), with a
compiled-program fallback (``memory_analysis``) for runtimes that expose no
live stats (e.g. the tunnel-attached axon backend and the CPU simulator).
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model, ShardingConfig
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


# New Code #
def device_peak_bytes():
    """Peak live bytes on this process's first device, or None when the
    runtime doesn't expose memory stats."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")


class PeakMemoryTracker:
    """Context manager printing the memory delta of the wrapped phase —
    the b2mb-style reporting of the reference example."""

    def __init__(self, accelerator, label):
        self.accelerator = accelerator
        self.label = label

    def __enter__(self):
        self.begin = device_peak_bytes()
        return self

    def __exit__(self, *exc):
        end = device_peak_bytes()
        if self.begin is None or end is None:
            self.accelerator.print(
                f"[{self.label}] runtime exposes no live memory stats "
                "(tunnel backend / CPU sim) — see the compiled estimate below"
            )
        else:
            self.accelerator.print(
                f"[{self.label}] peak device memory: {end / 2**20:.0f} MiB "
                f"(delta {max(0, end - (self.begin or 0)) / 2**20:.0f} MiB)"
            )


def training_function(config, args):
    # FSDP mesh: shard params over every local chip (the reference example
    # is specifically "fsdp WITH peak mem tracking")
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        sharding_config=ShardingConfig(fsdp=-1, data_parallel=1, min_weight_size_to_shard=1),
    )
    lr, num_epochs, seed = config["lr"], int(config["num_epochs"]), int(config["seed"])
    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()
    batch_size = int(config["batch_size"])

    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 128), eval_len=config.get("eval_len", 64),
    )
    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size,
        seq_len=min(model_config.max_seq_len, 128),
    )
    with PeakMemoryTracker(accelerator, "prepare"):
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            Model(model_def, variables), optax.adamw(lr), train_dataloader, eval_dataloader
        )

    for epoch in range(num_epochs):
        model.train()
        with PeakMemoryTracker(accelerator, f"train epoch {epoch}"):
            for batch in train_dl:
                outputs = model(
                    batch["input_ids"], attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"], labels=batch["labels"],
                    deterministic=False,
                )
                accelerator.backward(outputs["loss"])
                optimizer.step()
                optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for batch in eval_dl:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics(
                (predictions, batch["labels"])
            )
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    # New Code #
    # Compiled-program estimate: exact buffer accounting from XLA, available
    # on every backend (the number `bench.py` uses for the pipeline rows)
    engine = model._engine
    try:
        from accelerate_tpu.utils.serialization import flatten_pytree

        param_bytes = sum(
            leaf.nbytes for leaf in flatten_pytree(engine.params).values()
            if hasattr(leaf, "nbytes")
        )
        accelerator.print(
            f"[estimate] sharded param bytes this process: {param_bytes / 2**20:.2f} MiB"
        )
    except Exception as e:  # pragma: no cover
        accelerator.print(f"[estimate] unavailable: {e}")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="FSDP training with peak memory tracking.")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    if args.cpu:
        # env JAX_PLATFORMS=cpu is not enough on hosts whose sitecustomize
        # force-registers a TPU platform; set it before backend init
        jax.config.update("jax_platforms", "cpu")
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 2, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
